"""pafreport-compatible command line front end.

Mirrors the reference driver (pafreport.cpp:175-460): flag parsing with the
same optstring semantics, mode auto-selection by query FASTA file size,
per-(query,target) dedup in gene mode, refseq caching with an RC copy,
per-line diff extraction + report emission, and progressive MSA construction
under ``-w``.  Adds ``--device={cpu,tpu}``, ``--band``, ``--batch``,
``--motifs=FILE`` and an implemented ``-s`` summary (the reference parses
``-s`` but never writes it, SURVEY.md §2.5.1).

Usage:
  python -m pwasm_tpu.cli <paf_with_cg_cs> -r <refseq.fa> [-s <summary.txt>]
      [-o <diff_report.dfa>] [-w <outfile.mfa>] [-G|-F|-C|-N] [-D] [-v]
      [-c <clipmax>] [--device=cpu|tpu] [--motifs=FILE]
"""

from __future__ import annotations

import sys
from contextlib import contextmanager

from pwasm_tpu.core.config import (AUTO_FULLGENOME_FASTA_BYTES, Config,
                                   load_motifs)
from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import EXIT_USAGE, PwasmError
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.fasta import FastaFile
from pwasm_tpu.core.paf import AlnInfo, _atoi, parse_paf_line
from pwasm_tpu.report.diff_report import Summary

USAGE = """Usage:
 pafreport <paf_with_cg_cs> -r <refseq.fa> [-s <summary.txt>]
    [-o <diff_report.dfa>][-w <outfile.mfa>] [-G|-F|-C|-N]
    [--device=cpu|tpu] [--band=N] [--batch=N] [--motifs=FILE]

   <paf_with_cg_cs> is the input PAF file with high quality query sequence(s)
      aligned to many target sequences using minimap2 --cs
   -r provide the fasta file with query sequence(s) (required)
   -o write difference data for each alignment into <diff_report.dfa>
   -s write event summary counts into <summary.txt>
   -w write MSA as multifasta into <outfile.mfa>
   -G gene CDS analysis mode (default for query<100K; assumes -C)
   -F full genome alignment mode (default for query>100Kb; assumes -N)
   -C perform codon impact analysis
   -N skip codon impact analysis
   --realign   replace each alignment's PAF gap structure with a banded
               affine-gap DP re-alignment (device traceback) before MSA
               construction; requires an MSA output (-w/--ace/--info/--cons)
   --ace=FILE  write the refined MSA as an ACE contig (consensus calling)
   --info=FILE write the refined MSA as a contig-info table (per-seq pid)
   --cons=FILE write the consensus sequence as FASTA
   --remove-cons-gaps  drop all-gap consensus columns during refinement
   --no-refine-clip    skip the X-drop clipping refinement pass
   --skip-bad-lines    warn and continue on malformed PAF lines
   --resume    append to an existing -o report, skipping alignments
               already emitted (a -s summary then covers only the
               resumed portion); both report engines leave atomic
               batch-granular checkpoints (<report>.ckpt, versioned +
               CRC-validated), so a killed run resumes at the last
               completed batch exactly — a ckpt that fails
               verification is quarantined to <report>.ckpt.bad and
               the run restarts cleanly.  SIGTERM/SIGINT drain
               gracefully: the in-flight batch completes, a final
               checkpoint lands, and the run exits 75 ("preempted,
               resumable"; a second signal hard-aborts)
   --profile=DIR  write a jax.profiler device trace for the run
   --stats=FILE   write run statistics as one JSON object
   --trace-json=FILE  write host-side phase/batch spans (monotonic
               clock) as Chrome trace-event JSON — viewable in
               chrome://tracing / Perfetto alongside the --profile
               device dump (docs/OBSERVABILITY.md)
   --log-json=FILE|-  append structured NDJSON run-lifecycle events
               (breaker trips/recloses, OOM demotions, fallbacks,
               checkpoint writes, drains) with wall+monotonic
               timestamps and a run id; "-" streams to stdout
               (requires -o so events never share the report stream)
   --log-json-max-bytes=N  rotate the --log-json file once it passes
               N bytes (current file moves to FILE.1, one generation
               kept; a log_rotate event opens the fresh file) — a
               long-lived daemon's event log stays bounded
   --trace-max-events=N  cap the --trace-json recorder at N events
               (default 200000); drops are counted live in
               pwasm_trace_events_dropped_total and reported in the
               trace's otherData
   --metrics-textfile=PATH  write the run's metrics as Prometheus
               text exposition at end of run (atomic publish) for a
               node-exporter textfile collector
   --max-retries=N    re-execute a failed/rejected device batch up to
               N times (exponential backoff + jitter; default 2)
   --device-deadline=S  per-batch device deadline in seconds — a hung
               backend costs one timeout, not the run (default: none)
   --deadline-s=S  END-TO-END wall budget for the whole run: when it
               expires the run stops at its next batch boundary with
               a valid resumable checkpoint, prints the truth, and
               exits 75 (reason "deadline_exceeded" — resume with a
               fresh budget, or don't).  The serve daemon passes the
               REMAINING budget of a socket job down as this flag
               (docs/RESILIENCE.md; default: none)
   --fallback=cpu|fail  what exhausted retries do: degrade the batch
               to the bit-exact host path (cpu, default) or abort the
               run loudly (fail)
   --inject-faults=SPEC  debug: deterministic seeded fault injection
               into supervised device calls, e.g.
               seed=7,rate=0.3,kinds=raise+hang+nan+corrupt
               a scripted outage window down=A-B[+C-D], a scripted
               preemption preempt=N (graceful drain at supervised
               call N), or a simulated memory ceiling oom=N
               (see pwasm_tpu/resilience/faults.py for the spec)
   --recover=auto|off  auto (default): once the circuit breaker
               confirms a dead backend, keep re-probing it (bounded)
               and re-promote device work when it recovers; off: an
               open breaker degrades the rest of the run (PR-1
               behavior)
   --reprobe-interval=S  first re-probe delay after the breaker opens
               (default 5; doubles per unhealthy probe)
   --reprobe-max=S     ceiling of the capped-exponential re-probe
               schedule (default 300)
   --shard[=N]    (with --device=tpu) shard the device work over a mesh
               of N chips (default: all visible): the analysis batch
               spreads over the mesh and consensus pileup counts are
               psum-reduced over the depth axis before the vote
   --follow[=IDLE_S]  streaming ingestion (docs/STREAMING.md): tail
               the input PAF as a GROWING file, emitting report bytes
               as batches fill (rotation-safe tail -F semantics;
               partial lines wait for their newline).  With =IDLE_S
               the stream ends after IDLE_S seconds without growth
               and the run completes normally; bare --follow tails
               until SIGTERM (which drains to exit 75, resumable)
   --compile-cache-dir=DIR  persistent XLA compilation-cache location
               for the device path (via the jaxcompat shim; default
               PWASM_JAX_CACHE_DIR or ~/.cache/pwasm_tpu/jax) — a
               fleet member restarted on the same DIR skips its
               compile wall (docs/FLEET.md)
   --result-cache=DIR|off  content-addressed RESULT cache
               (docs/SERVICE.md): a completed run's output files are
               stored under sha256(ref-FASTA digest, input digest,
               result-affecting flags, output kinds), and an
               identical later run — cosmetic argv reorders and
               output paths excluded — is served the stored bytes in
               microseconds instead of re-running.  CRC-verified on
               every serve (rot = miss, never a corrupt serve);
               --resume/--follow/--inject-faults and unknown flags
               bypass.  The serve daemon consults the same cache at
               admission (serve --result-cache)
   --result-cache-max-bytes=N  evict least-recently-used cache
               entries past N total bytes
   --many2many    multi-CDS scoring job (docs/STREAMING.md): score
               EVERY query in the -r FASTA against every target in
               the positional FASTA through ONE device session
               (banded DP, parallel/many2many.py) — per-CDS report
               sections byte-identical to N single-CDS runs

 Warm-pool service (docs/SERVICE.md): a resident daemon that keeps the
 process warm (one backend probe, one compile cache, one breaker +
 health monitor) and multiplexes report jobs over a unix socket:
   pwasm-tpu serve --socket=PATH [--max-queue=N] [--max-concurrent=N]
   pwasm-tpu submit --socket=PATH [--no-wait] [--] <cli args...>
   pwasm-tpu stream --socket=PATH [--] <cli args...>   (PAF on stdin,
               streamed record-at-a-time — the minimap2-pipe shape)
   pwasm-tpu svc-stats --socket=PATH [--drain]
   pwasm-tpu metrics --socket=PATH   (Prometheus text exposition)
   pwasm-tpu inspect --socket=PATH JOB_ID   (the job's flight record:
               phase-accounted walls — queue/lease/exec, per-flush
               device/host/format — plus its event ring)
   pwasm-tpu top --socket=PATH [--interval=S] [--once]   (live fleet
               view: lanes, per-client queues, streams, breakers)
   pwasm-tpu trace-merge CLIENT.json DAEMON.json [-o OUT.json]
               (one wall-anchored cross-process Perfetto timeline)
   pwasm-tpu route --backends=a.sock,hostB:9211 --socket=PATH
               (fleet router, docs/FLEET.md: N daemons — unix and/or
               TCP `serve --listen` members — behind one submit
               surface, least-loaded placement, fleet-wide fair
               share, journal-aware failover)
"""

# reference optstring: "DGFCNvd:p:r:o:m:w:c:s:" — -d/-p/-m take a value but
# are never read (quirk SURVEY.md §2.5.2)
_BOOL_FLAGS = set("DGFCNvh")
_VALUE_FLAGS = set("dprmowcs")

# warm-pool service subcommands (pwasm_tpu/service/, docs/SERVICE.md):
# `pwasm-tpu serve` starts the resident daemon, the rest are the
# client side — dispatched on the FIRST argv token so the classic flag
# grammar stays untouched for plain runs.  `trace-merge` is the
# offline cross-process trace join (no socket, pwasm_tpu/obs/merge.py)
_SERVICE_CMDS = ("serve", "submit", "svc-stats", "metrics", "stream",
                 "inspect", "top", "trace-merge", "route", "health",
                 "logs")


class CliError(PwasmError):
    exit_code = EXIT_USAGE


def _parse_args(argv: list[str]) -> tuple[dict, list[str]]:
    """GArgs-style parser: single-letter flags (joined or separated values)
    plus --long=value options."""
    opts: dict[str, str | bool] = {}
    positional: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            if "=" in a:
                k, v = a[2:].split("=", 1)
                opts[k] = v
            else:
                opts[a[2:]] = True
        elif a.startswith("-") and len(a) > 1:
            j = 1
            while j < len(a):
                ch = a[j]
                if ch in _BOOL_FLAGS:
                    opts[ch] = True
                    j += 1
                elif ch in _VALUE_FLAGS:
                    if j + 1 < len(a):
                        opts[ch] = a[j + 1:]
                    else:
                        i += 1
                        if i >= len(argv):
                            raise CliError(
                                f"{USAGE}\nInvalid argument: -{ch}\n")
                        opts[ch] = argv[i]
                    j = len(a)
                else:
                    raise CliError(f"{USAGE}\nInvalid argument: {a}\n")
        else:
            positional.append(a)
        i += 1
    return opts, positional


def _parse_clipmax(s: str, verbose: bool) -> float:
    """-c parsing (pafreport.cpp:217-240)."""
    ispercent = s.endswith("%")
    if ispercent:
        s = s.rstrip("%")
    c = _atoi(s)  # GStr::asInt has C atoi semantics: "12x" parses as 12
    if c <= 0:
        raise PwasmError(
            f"Error: invalid -c <clipmax> ({c}) option provided (must be "
            "a positive integer)!\n")
    if ispercent and c > 99:
        raise PwasmError(
            f"Error: invalid percent value ({c}) for -c option "
            " (must be an integer between 1 and 99)!\n")
    if ispercent:
        clipmax = float(c) / 100
        if verbose:
            print(f"Percentual max clipping set to {c}%", file=sys.stderr)
        return clipmax
    if verbose:
        print(f"Max clipping set to {c} bases", file=sys.stderr)
    return float(c)


def _ckpt_path(report_path: str) -> str:
    return report_path + ".ckpt"


# Checkpoint format v2 (self-validating): the v1 ckpt was unversioned,
# unchecksummed JSON — a torn or bit-rotted remnant that still parsed
# could silently poison a resumed run.  v2 wraps the payload
# ({bytes, records, resilience}) with a version tag and a CRC32 over
# the payload's canonical JSON encoding, and _load_checkpoint verifies
# BOTH plus a report-tail boundary check (the recorded byte offset must
# land exactly on a record boundary of the actual report file).  Any
# failure quarantines the ckpt to <report>.ckpt.bad and the run
# restarts cleanly — never resumes onto garbage.
CKPT_VERSION = 2
_CKPT_META = ("version", "crc")   # non-payload keys, excluded from CRC


def _ckpt_crc(ck: dict) -> int:
    """CRC32 over the ckpt's payload fields in canonical JSON form
    (``fsio.payload_crc`` — the shared self-validating-state
    checksum)."""
    from pwasm_tpu.utils.fsio import payload_crc

    return payload_crc({k: v for k, v in ck.items()
                        if k not in _CKPT_META})


def _on_record_boundary(report_path: str, nbytes: int) -> bool:
    """True when byte offset ``nbytes`` of the report is a record
    boundary: 0, or preceded by a newline with either EOF or the next
    record's ``>`` header right after (a ckpt whose offset lands
    mid-record describes a prefix that was never durable as claimed)."""
    import os

    try:
        size = os.path.getsize(report_path)
        if nbytes == 0:
            return True
        if nbytes > size:
            return False
        with open(report_path, "rb") as f:
            if f.read(1) != b">":
                return False     # not a report of this tool
            f.seek(nbytes - 1)
            if f.read(1) != b"\n":
                return False
            if nbytes < size and f.read(1) != b">":
                return False
        return True
    except OSError:
        return False


def _load_checkpoint(report_path: str) \
        -> tuple[int, int, dict | None] | str | None:
    """Read and VERIFY the batch-granular resume checkpoint for
    ``report_path``.  Returns ``(bytes, records, resilience_state)``
    when the ckpt is whole (version + CRC verified, offset on a record
    boundary of the actual report); ``None`` when no ckpt file exists
    (the header-scan heuristic applies); or a ``str`` diagnostic when a
    ckpt EXISTS but is torn/corrupt/inconsistent — the caller must
    quarantine it and restart cleanly rather than resume onto
    garbage."""
    import json
    import os

    try:
        with open(_ckpt_path(report_path)) as f:
            raw = f.read()
    except OSError:
        return None
    try:
        ck = json.loads(raw)
        if not isinstance(ck, dict):
            raise ValueError("not an object")
    except ValueError as e:
        return f"unparseable ckpt JSON ({e})"
    if ck.get("version") != CKPT_VERSION:
        return f"ckpt version {ck.get('version')!r} != {CKPT_VERSION}"
    try:
        crc = int(ck["crc"])
        nbytes, nrec = ck["bytes"], ck["records"]
        if not (isinstance(nbytes, int) and isinstance(nrec, int)):
            raise TypeError("bytes/records not ints")
    except (KeyError, TypeError, ValueError) as e:
        return f"malformed ckpt fields ({e})"
    if crc != _ckpt_crc(ck):
        return "ckpt payload CRC mismatch"
    if nbytes < 0 or nrec < 0 or (nbytes == 0) != (nrec == 0):
        return f"inconsistent ckpt counts (bytes={nbytes}, " \
               f"records={nrec})"
    try:
        if nbytes > os.path.getsize(report_path):
            return f"ckpt bytes {nbytes} past the report's " \
                   f"{os.path.getsize(report_path)}"
    except OSError as e:
        return f"report unreadable ({e})"
    if not _on_record_boundary(report_path, nbytes):
        return f"ckpt offset {nbytes} is not a record boundary of " \
               "the report"
    res = ck.get("resilience")
    return nbytes, nrec, res if isinstance(res, dict) else None


def _quarantine_checkpoint(report_path: str, why: str, stderr) -> None:
    """Move a failed-verification ckpt aside to ``<report>.ckpt.bad``
    (preserved for post-mortem, out of every future resume's way) and
    say so loudly."""
    import os

    from pwasm_tpu.utils.fsio import replace_durable

    try:
        replace_durable(_ckpt_path(report_path),
                        _ckpt_path(report_path) + ".bad")
    except OSError:
        try:
            os.unlink(_ckpt_path(report_path))
        except OSError:
            pass
    print(f"Warning: checkpoint failed verification ({why}); "
          f"quarantined to {_ckpt_path(report_path)}.bad — "
          "restarting the run from scratch instead of resuming onto "
          "a corrupt prefix", file=stderr)


def _write_checkpoint(freport, report_path: str, records: int,
                      res_state: dict | None = None) -> bool:
    """Atomically AND durably persist the report's durable prefix after
    one completed batch: fsync the report, then publish the v2
    (versioned, CRC'd) ckpt JSON via the audited fsync-then-replace
    (``utils.fsio``: tmp write + tmp fsync + rename + parent-dir
    fsync — a crash at any instant leaves the old ckpt or the new one,
    never a torn or empty file that merely *looks* atomic).
    ``res_state`` rides along (breaker / monitor / fault-plan /
    bucket-ceiling snapshot) so a ``--resume`` after a kill inherits
    mid-outage state.  Best-effort — a failed write never stops the run
    (returns False)."""
    import json
    import os

    from pwasm_tpu.utils.fsio import write_durable_text

    try:
        freport.flush()
        os.fsync(freport.fileno())
        size = os.fstat(freport.fileno()).st_size
        ck = {"version": CKPT_VERSION, "bytes": size,
              "records": records}
        if res_state is not None:
            ck["resilience"] = res_state
        ck["crc"] = _ckpt_crc(ck)
        write_durable_text(_ckpt_path(report_path), json.dumps(ck),
                           tmp_suffix=".tmp")
        return True
    except OSError:
        return False


def _unlink_checkpoint(report_path: str) -> None:
    import os

    try:
        os.unlink(_ckpt_path(report_path))
    except OSError:
        pass


def warmup_files(dirpath: str) -> tuple[str, str]:
    """Write the deterministic warmup corpus (``serve --warmup``):
    a tiny query FASTA + PAF whose alignments exercise the ctx-scan
    device program on the smallest pow2 event/ref buckets — enough to
    pay the jax import, backend init and first compiles (and populate
    ``--compile-cache-dir``) before a daemon's first real job.  Pure
    host-side text generation: no jax, no randomness."""
    import os

    from pwasm_tpu.utils.fsio import ensure_private_dir
    ensure_private_dir(dirpath)
    q = "ACGT" * 30                       # 120-base query
    fa = os.path.join(dirpath, "warm.fa")
    with open(fa, "w") as f:
        f.write(f">warmq\n{q}\n")
    lines = []
    for i in range(16):
        p = 10 + 6 * i                    # substitution position
        qb = q[p]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        cs = f":{p}*{tb.lower()}{qb.lower()}:{len(q) - p - 1}"
        tseq_len = len(q)
        lines.append("\t".join([
            "warmq", str(len(q)), "0", str(len(q)), "+",
            f"warmt{i}", str(tseq_len), "0", str(tseq_len),
            str(len(q)), str(len(q)), "60", "NM:i:1", "AS:i:0",
            f"cg:Z:{len(q)}M", f"cs:Z:{cs}"]))
    paf = os.path.join(dirpath, "warm.paf")
    with open(paf, "w") as f:
        f.write("".join(ln + "\n" for ln in lines))
    return paf, fa


def run(argv: list[str], stdout=None, stderr=None, warm=None,
        input_stream=None) -> int:
    """One CLI invocation.  ``warm`` is the warm-pool service hook
    (``service.daemon.WarmContext`` shape): a resident serve process
    passes one per job so consecutive jobs share the drain flag, the
    backend health monitor, and the supervisor's breaker/ceiling state
    — a cold run (warm=None) behaves exactly as before.
    ``input_stream`` is the socket-stream hook (docs/STREAMING.md): an
    iterable of PAF lines (``stream.pafstream.StreamFeed`` shape) the
    serve daemon substitutes for the input file when the job arrived
    via ``stream`` protocol frames — the loop, batching, and
    checkpoint machinery are identical either way, which is the
    byte-parity contract."""
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    if argv and argv[0] in _SERVICE_CMDS:
        try:
            if argv[0] == "serve":
                from pwasm_tpu.service.daemon import serve_main
                return serve_main(argv[1:], stdout, stderr)
            if argv[0] == "route":
                from pwasm_tpu.fleet.router import route_main
                return route_main(argv[1:], stdout, stderr)
            if argv[0] == "trace-merge":
                from pwasm_tpu.obs.merge import trace_merge_main
                return trace_merge_main(argv[1:], stdout, stderr)
            if argv[0] == "top":
                from pwasm_tpu.service.top import top_main
                return top_main(argv[1:], stdout, stderr)
            from pwasm_tpu.service.client import client_main
            return client_main(argv[0], argv[1:], stdout, stderr)
        except PwasmError as e:
            stderr.write(str(e))
            return e.exit_code
    opts, positional = _parse_args(argv)
    if opts.get("h"):
        stderr.write(USAGE + "\n")
        return EXIT_USAGE
    if opts.get("many2many"):
        # the multi-CDS job type (ISSUE 10b): one device session for
        # every query in the -r FASTA — jax-free host driver in
        # pwasm_tpu/stream/multicds.py, device work via the supervised
        # many2many site
        from pwasm_tpu.stream.multicds import many2many_main
        try:
            return many2many_main(opts, positional, stdout, stderr,
                                  warm=warm)
        except PwasmError as e:
            stderr.write(str(e))
            return e.exit_code
    if opts.get("m2m-stream"):
        # continuous many2many (ROADMAP item 3): targets arrive
        # incrementally — over the stream verbs when served
        # (input_stream), from a FASTA replayed as a stream when cold
        # — and score against the resident -r query set with
        # incremental per-CDS section emission (pwasm_tpu/surveil/)
        from pwasm_tpu.surveil.session import m2m_stream_main
        try:
            return m2m_stream_main(opts, positional, stdout, stderr,
                                   warm=warm,
                                   input_stream=input_stream)
        except PwasmError as e:
            stderr.write(str(e))
            return e.exit_code

    cfg = Config()
    cfg.debug = bool(opts.get("D"))
    cfg.fullgenome = bool(opts.get("F"))
    gene_cds = bool(opts.get("G"))
    if cfg.fullgenome and gene_cds:
        stderr.write(f"{USAGE} Error: cannot use both -G and -F!\n")
        return EXIT_USAGE
    force_coding = bool(opts.get("C"))
    force_noncoding = bool(opts.get("N"))
    if force_coding and force_noncoding:
        stderr.write(f"{USAGE} Error: cannot use both -N and -C!\n")
        return EXIT_USAGE
    cfg.verbose = bool(opts.get("v")) or cfg.debug
    cfg.gene_cds = gene_cds
    cfg.device = str(opts.get("device", "cpu"))
    if cfg.device not in ("cpu", "tpu"):
        raise CliError(f"{USAGE}\nInvalid --device value: {cfg.device} "
                       "(must be cpu or tpu)\n")
    for knob in ("band", "batch"):
        if knob in opts:
            val = opts[knob]
            if val is True or not str(val).isascii() \
                    or not str(val).isdigit() or int(val) < 1:
                raise CliError(
                    f"{USAGE}\nInvalid --{knob} value: {val}\n")
            setattr(cfg, knob, int(val))
    if opts.get("motifs") is True:
        raise CliError(f"{USAGE}\n--motifs requires a file argument\n")
    if "shard" in opts:
        val = opts["shard"]
        if val is True:
            cfg.shard = -1          # all visible devices
        elif str(val).isascii() and str(val).isdigit() and int(val) >= 1:
            cfg.shard = int(val)
        else:
            stderr.write(f"{USAGE}\nInvalid --shard value: {val}\n")
            return EXIT_USAGE
        if cfg.device != "tpu":
            stderr.write(f"{USAGE} Error: --shard requires "
                         "--device=tpu!\n")
            return EXIT_USAGE
    cfg.realign = bool(opts.get("realign"))
    if cfg.realign and "w" not in opts \
            and not any(k in opts for k in ("ace", "info", "cons")):
        stderr.write(f"{USAGE} Error: --realign requires an MSA output "
                     "(-w, --ace, --info or --cons)!\n")
        return EXIT_USAGE

    infile = positional[0] if positional else None
    if infile == "-":
        # the conventional stdin marker (the pipe shape the service
        # layer's _absolutize_argv already passes through untouched)
        infile = None
    inf = sys.stdin
    obs = None          # the observability bundle (closed on unwind)
    opened: list = []   # output handles closed on ANY unwind: a killed
    # run must not leave a buffered handle whose late GC flush could
    # write stale bytes past a checkpoint-truncated report
    # --follow[=IDLE_S]: streaming ingestion over a growing input file
    # (docs/STREAMING.md).  bare --follow tails until a signal drains
    # the run; =IDLE_S ends the stream after that long without growth.
    follow = "follow" in opts
    follow_idle: float | None = None
    if follow:
        val = opts["follow"]
        if val is not True:
            import math as _m
            try:
                follow_idle = float(str(val))
                if follow_idle <= 0 or not _m.isfinite(follow_idle):
                    raise ValueError
            except (TypeError, ValueError):
                raise CliError(f"{USAGE}\nInvalid --follow value: "
                               f"{val}\n")
        if infile is None and input_stream is None:
            raise CliError(f"{USAGE}\n--follow requires an input PAF "
                           "file to tail (stdin already streams)\n")
    # content-addressed result cache (ISSUE 15 / ROADMAP item 2): an
    # identical job — same inputs by digest, same result-affecting
    # flags by canonical form — serves its stored output bytes instead
    # of re-running the pipeline.  service/cache.py owns the key
    # derivation (the SAME derivation the serve daemon applies at
    # admission, so cold runs populate what warm serving hits).
    cache_store = None
    cache_key_hex = None
    cache_cls = None
    follow_cls = None      # --follow's classify-without-the-flag view
    cache_delta = None     # (records served, records total) when the
    #                        run was re-armed as a delta over a cached
    #                        prefix (ISSUE 17)
    rc_dir = opts.get("result-cache")
    if rc_dir is True:
        raise CliError(f"{USAGE}\n--result-cache requires a "
                       "directory (or off)\n")
    rc_max = None
    if "result-cache-max-bytes" in opts:
        val = opts["result-cache-max-bytes"]
        if val is True or not str(val).isascii() \
                or not str(val).isdigit() or int(val) < 1:
            raise CliError(
                f"{USAGE}\nInvalid --result-cache-max-bytes value: "
                f"{val}\n")
        rc_max = int(val)
    try:
        if isinstance(rc_dir, str) and rc_dir and rc_dir != "off" \
                and input_stream is None:
            from pwasm_tpu.service.cache import (CacheStore, classify,
                                                 derive_key,
                                                 serve_outputs)
            cache_cls = classify(opts, positional)
            if cache_cls is None and follow:
                # a --follow job bypasses the exact cache (its input
                # is still growing) but re-enters through the DELTA
                # path: classified without the flag, the grown file's
                # prefix may already be a cached entry — a restart on
                # a grown file then becomes a cache hit plus a tail
                # of new records (ISSUE 17a / docs/STREAMING.md)
                follow_cls = classify(
                    {k: v for k, v in opts.items() if k != "follow"},
                    positional)
            if cache_cls is not None:
                cache_key_hex = derive_key(cache_cls)
            if cache_key_hex is not None or follow_cls is not None:
                try:
                    cache_store = CacheStore(rc_dir, max_bytes=rc_max)
                except OSError as e:
                    print(f"Warning: --result-cache dir {rc_dir} "
                          f"unusable ({e}); caching disabled",
                          file=stderr)
            if cache_store is not None and cache_key_hex is not None:
                got = cache_store.get(cache_key_hex)
                served = False
                if got is not None:
                    try:
                        served = serve_outputs(got[1],
                                               cache_cls.output_paths)
                    except OSError:
                        served = False   # unwritable output: fall
                        #   through to the real run, which reports
                        #   the canonical "Cannot open file ..."
                if served:
                    return _serve_cache_hit(got[0], opts, stderr,
                                            verbose=bool(
                                                opts.get("v")))
            if cache_store is not None:
                # exact miss: a same-family entry whose input is a
                # per-line PREFIX of ours serves its cached report and
                # re-arms this run as a --resume over it — only the
                # last cached record and the appended tail recompute
                cache_delta = _cache_delta_serve(
                    cache_store, follow_cls or cache_cls, opts,
                    stderr, allow_equal=follow,
                    verbose=bool(opts.get("v")))
        if input_stream is not None:
            if infile is not None:
                raise PwasmError(
                    "Error: a socket-streamed job reads records from "
                    "the stream — drop the positional PAF path!\n")
            inf = input_stream
        elif infile:
            if follow:
                import hashlib as _fhash
                from pwasm_tpu.stream.pafstream import FollowReader
                # with the result cache armed, the follow pass rides
                # the same content hasher the block reader does: a
                # cleanly idle-ended follow populates the cache
                inf = FollowReader(infile, idle_timeout_s=follow_idle,
                                   hasher=_fhash.sha256()
                                   if cache_store is not None else None)
            else:
                # block-scan ingest (ROADMAP item 5): the host
                # path walks the input in 1 MiB blocks through the
                # stream layer's LineAssembler instead of per-record
                # readline calls — byte-identical to the text-mode
                # read by the assembler's universal-newline contract
                # (PWASM_MMAP_INGEST=0 is the A/B hatch; the reader
                # deliberately avoids mmap — SIGBUS on a concurrently
                # truncated input would kill a serve daemon whole).
                # With the result cache armed, the pass also feeds the
                # content hasher, so the insert-side key costs no
                # second read of the input.
                import hashlib as _hashlib
                import os as _os
                try:
                    if _os.environ.get("PWASM_MMAP_INGEST",
                                       "1") != "0":
                        from pwasm_tpu.stream.pafstream import \
                            BlockLineReader
                        inf = BlockLineReader(
                            infile,
                            hasher=_hashlib.sha256()
                            if cache_store is not None else None)
                    else:
                        inf = open(infile)
                except OSError:
                    raise PwasmError(
                        f"Cannot open input file {infile}!\n")
        if "motifs" in opts:
            try:
                cfg.motifs = load_motifs(str(opts["motifs"]))
            except (OSError, UnicodeDecodeError):
                raise PwasmError(
                    f"Cannot open motif file {opts['motifs']}!\n")
        if "c" in opts:
            cfg.clipmax = _parse_clipmax(str(opts["c"]), cfg.verbose)
        cfg.skip_bad_lines = bool(opts.get("skip-bad-lines"))
        cfg.resume = bool(opts.get("resume"))
        if "max-retries" in opts:
            val = opts["max-retries"]
            if val is True or not str(val).isascii() \
                    or not str(val).isdigit():
                raise CliError(f"{USAGE}\nInvalid --max-retries value: "
                               f"{val}\n")
            cfg.max_retries = int(val)
        if "device-deadline" in opts:
            import math
            try:
                cfg.device_deadline = float(str(opts["device-deadline"]))
                # nan survives a <= 0 check and would poison every
                # thread join; inf is an unbounded "deadline" — both
                # are usage errors, not policies
                if cfg.device_deadline <= 0 \
                        or not math.isfinite(cfg.device_deadline):
                    raise ValueError
            except (TypeError, ValueError):
                raise CliError(f"{USAGE}\nInvalid --device-deadline "
                               f"value: {opts['device-deadline']}\n")
        if "deadline-s" in opts:
            import math
            try:
                cfg.deadline_s = float(str(opts["deadline-s"]))
                if cfg.deadline_s <= 0 \
                        or not math.isfinite(cfg.deadline_s):
                    raise ValueError
            except (TypeError, ValueError):
                raise CliError(f"{USAGE}\nInvalid --deadline-s "
                               f"value: {opts['deadline-s']}\n")
        if "fallback" in opts:
            cfg.fallback = str(opts["fallback"])
            if cfg.fallback not in ("cpu", "fail"):
                raise CliError(f"{USAGE}\nInvalid --fallback value: "
                               f"{cfg.fallback} (must be cpu or fail)\n")
        if "recover" in opts:
            cfg.recover = str(opts["recover"])
            if cfg.recover not in ("auto", "off"):
                raise CliError(f"{USAGE}\nInvalid --recover value: "
                               f"{cfg.recover} (must be auto or off)\n")
        import math as _math
        for knob, attr in (("reprobe-interval", "reprobe_interval"),
                           ("reprobe-max", "reprobe_max")):
            if knob in opts:
                try:
                    v = float(str(opts[knob]))
                    if v < 0 or not _math.isfinite(v):
                        raise ValueError
                except (TypeError, ValueError):
                    raise CliError(f"{USAGE}\nInvalid --{knob} value: "
                                   f"{opts[knob]}\n")
                setattr(cfg, attr, v)
        if cfg.reprobe_max < cfg.reprobe_interval:
            if "reprobe-max" in opts and "reprobe-interval" in opts:
                raise CliError(
                    f"{USAGE}\nInvalid --reprobe-max value: "
                    f"{cfg.reprobe_max:g} (must be >= --reprobe-interval "
                    f"{cfg.reprobe_interval:g})\n")
            # only one side was set: move the DEFAULT of the other side
            # to keep a self-consistent request consistent — a raised
            # interval lifts the default ceiling, a lowered ceiling
            # pulls the default first-probe delay down with it
            if "reprobe-max" in opts:
                cfg.reprobe_interval = cfg.reprobe_max
            else:
                cfg.reprobe_max = cfg.reprobe_interval
        if "inject-faults" in opts:
            if opts["inject-faults"] is True:
                raise CliError(
                    f"{USAGE}\n--inject-faults requires a spec\n")
            cfg.inject_faults = str(opts["inject-faults"])
            from pwasm_tpu.resilience.faults import parse_fault_spec
            try:
                parse_fault_spec(cfg.inject_faults)
            except ValueError as e:
                raise CliError(f"{USAGE}\nInvalid --inject-faults: "
                               f"{e}\n")
        for kind in ("profile", "stats", "trace-json", "log-json",
                     "metrics-textfile", "compile-cache-dir"):
            if opts.get(kind) is True:
                raise CliError(
                    f"{USAGE}\n--{kind} requires a file argument\n")
        cfg.compile_cache_dir = str(opts.get("compile-cache-dir", ""))
        if "profile" in opts:
            cfg.profile_dir = str(opts["profile"])
        if "stats" in opts:
            cfg.stats_path = str(opts["stats"])
        cfg.trace_json = str(opts.get("trace-json", ""))
        cfg.log_json = str(opts.get("log-json", ""))
        cfg.metrics_textfile = str(opts.get("metrics-textfile", ""))
        for knob, attr in (("trace-max-events", "trace_max_events"),
                           ("log-json-max-bytes",
                            "log_json_max_bytes")):
            if knob in opts:
                val = opts[knob]
                if val is True or not str(val).isascii() \
                        or not str(val).isdigit() or int(val) < 1:
                    raise CliError(
                        f"{USAGE}\nInvalid --{knob} value: {val}\n")
                setattr(cfg, attr, int(val))
        if cfg.log_json == "-" and "o" not in opts:
            # without -o the report itself streams to stdout — event
            # lines interleaved with report rows would corrupt both
            raise CliError(
                f"{USAGE}\n--log-json=- requires -o <report> (stdout "
                "already carries the report)\n")
        resume_skip = 0
        resume_state: dict | None = None
        ckpt_quarantined = False
        if cfg.resume:
            if "o" not in opts:
                raise CliError(f"{USAGE}\n--resume requires -o <report>\n")
            # Checkpoint-first resume (the device/MSA-path durability
            # journal): a batch-granular <report>.ckpt names the exact
            # byte size and record count of the last COMPLETED batch —
            # truncate any torn tail past it and skip exactly those
            # records, no re-emission.  The ckpt is SELF-VALIDATING
            # (version + payload CRC + record-boundary check against
            # the actual report): a ckpt that exists but fails any
            # check is quarantined to <report>.ckpt.bad and the run
            # RESTARTS CLEANLY — a bad journal must never half-resume
            # via the header-scan heuristic below, which only applies
            # when no ckpt was written at all.
            ck = _load_checkpoint(str(opts["o"]))
            from pwasm_tpu.utils.fsio import truncate_durable
            if isinstance(ck, str):
                _quarantine_checkpoint(str(opts["o"]), ck, stderr)
                ckpt_quarantined = True
                try:
                    truncate_durable(str(opts["o"]), 0)
                except OSError:
                    pass
            elif ck is not None:
                nbytes, resume_skip, resume_state = ck
                try:
                    truncate_durable(str(opts["o"]), nbytes)
                except OSError:
                    resume_skip = 0
                    resume_state = None
        if cfg.resume and resume_skip == 0 and not ckpt_quarantined:
            # The report is per-alignment independent in report mode:
            # resume = drop the LAST record (its event rows may be torn
            # by the interruption — a header alone doesn't prove the rows
            # landed), truncate there, count the surviving headers, and
            # skip that many accepted alignments (SURVEY.md §5
            # checkpoint/resume).  The dropped record is re-emitted.
            try:
                # stream in chunks (reports can be GBs): count record
                # headers and remember where the last one starts
                n_headers = 0
                last_header = -1
                size = 0
                prev_byte = b"\n"  # virtual newline before file start
                with open(str(opts["o"]), "rb") as f:
                    starts_ok = f.read(1) == b">"
                    f.seek(0)
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        search = prev_byte + chunk
                        pos = search.find(b"\n>")
                        while pos != -1:
                            n_headers += 1
                            # search[pos] is the byte BEFORE the '>', so
                            # the record starts at file offset size + pos
                            last_header = size + pos
                            pos = search.find(b"\n>", pos + 1)
                        prev_byte = chunk[-1:]
                        size += len(chunk)
                if starts_ok and n_headers > 0:
                    # drop the LAST record: its rows may be torn
                    keep = last_header if n_headers > 1 else 0
                    resume_skip = n_headers - 1
                else:
                    keep, resume_skip = 0, 0  # not a report of this tool
                if keep != size:
                    # same durability contract as the ckpt-driven
                    # truncate above: the dropped torn record must
                    # stay dropped across a crash
                    from pwasm_tpu.utils.fsio import truncate_durable
                    truncate_durable(str(opts["o"]), keep)
            except OSError:
                resume_skip = 0  # nothing emitted yet: a fresh run
        if not cfg.resume and "o" in opts:
            # a fresh run invalidates any checkpoint left by a killed
            # predecessor writing the same report path
            _unlink_checkpoint(str(opts["o"]))
        try:
            mode = "a" if cfg.resume else "w"
            freport = open(str(opts["o"]), mode) if "o" in opts else stdout
            if freport is not stdout:
                opened.append(freport)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['o']} for writing!\n")
        rpath = opts.get("r")
        if not rpath:
            raise PwasmError("Error: query FASTA file (-r) is required!\n")
        try:
            qfasta = FastaFile(str(rpath))
        except OSError:
            raise PwasmError(f"Error: invalid FASTA file {rpath} !\n")
        fsize = qfasta.file_size()
        if fsize <= 0:
            raise PwasmError(f"Error: invalid FASTA file {rpath} !\n")
        if not cfg.fullgenome and not gene_cds \
                and fsize > AUTO_FULLGENOME_FASTA_BYTES:
            cfg.fullgenome = True
        cfg.skip_codan = cfg.fullgenome or force_noncoding
        if not cfg.skip_codan and not force_coding \
                and fsize > AUTO_FULLGENOME_FASTA_BYTES:
            cfg.skip_codan = True
        fmsa = None
        cons_outs = {}   # kind -> open file, kinds: ace, info, cons
        if "w" in opts or any(k in opts for k in ("ace", "info", "cons")):
            if cfg.fullgenome:
                stderr.write(
                    f"{USAGE} Error: can only generate MSA for -G mode!\n")
                return EXIT_USAGE
            if "w" in opts:
                try:
                    fmsa = open(str(opts["w"]), "w")
                    opened.append(fmsa)
                except OSError:
                    raise PwasmError(
                        f"Cannot open file {opts['w']} for writing!\n")
            for kind in ("ace", "info", "cons"):
                if opts.get(kind) is True:
                    raise CliError(
                        f"{USAGE}\n--{kind} requires a file argument\n")
            for kind in ("ace", "info", "cons"):
                if kind in opts:
                    try:
                        cons_outs[kind] = open(str(opts[kind]), "w")
                        opened.append(cons_outs[kind])
                    except OSError:
                        raise PwasmError(
                            f"Cannot open file {opts[kind]} for writing!\n")
        cfg.remove_cons_gaps = bool(opts.get("remove-cons-gaps"))
        cfg.refine_clipping = not bool(opts.get("no-refine-clip"))
        try:
            fsummary = open(str(opts["s"]), "w") if "s" in opts else None
            if fsummary is not None:
                opened.append(fsummary)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['s']} for writing!\n")
        summary = Summary() if fsummary else None

        from pwasm_tpu.obs import make_observability
        from pwasm_tpu.resilience.lifecycle import SignalDrain
        from pwasm_tpu.utils import device_trace
        # --trace-json / --log-json / --metrics-textfile: the jax-free
        # observability bundle (pwasm_tpu.obs).  Strictly additive: it
        # writes only to its own sinks, never the report stream — the
        # byte-parity test (flags on vs off) holds by construction.
        # a served job inherits the daemon-minted identity + flight
        # recorder (warm._JobWarm): the trace_id stamps every event
        # line as run_id, and the run's spans accumulate phase walls
        # on the job's flight record (docs/OBSERVABILITY.md)
        trace_id = getattr(warm, "trace_id", None) \
            if warm is not None else None
        flight = getattr(warm, "flight", None) \
            if warm is not None else None
        try:
            obs = make_observability(
                cfg.trace_json or None, cfg.log_json or None,
                cfg.metrics_textfile or None, stdout=stdout,
                trace_max_events=cfg.trace_max_events or None,
                log_json_max_bytes=cfg.log_json_max_bytes or None,
                run_id=trace_id, flight=flight)
        except OSError:
            raise PwasmError(
                f"Cannot open file {cfg.log_json} for writing!\n")
        if obs.enabled:
            obs.event("run_start", device=cfg.device, argv=list(argv))
        # graceful drain (SURVEY.md §5 / docs/RESILIENCE.md): the first
        # SIGTERM/SIGINT only raises a flag the batch loop honors at
        # the next batch boundary — in-flight work completes, a final
        # checkpoint + partial --stats land, and the exit code says
        # "preempted, resumable" (75); a second signal hard-aborts.
        # A warm serve process supplies the drain itself (per job, its
        # signal surface is the DAEMON's handler fanning out to these
        # flags — install() is a no-op off the main thread anyway).
        drain_cm = warm.drain if warm is not None \
            and warm.drain is not None else SignalDrain(stderr=stderr)
        if obs.enabled:
            drain_cm.obs = obs   # the drain request itself is a
            #                      lifecycle event worth logging
        # ---- end-to-end deadline (ISSUE 18): --deadline-s rides the
        # SAME graceful-drain machinery a SIGTERM uses — a timer pulls
        # the flag when the wall budget runs out, the batch loop stops
        # at its next boundary, a valid resumable checkpoint + partial
        # stats land, and the exit says preempted (75) with reason
        # "deadline_exceeded: ..." so the daemon can map the verdict
        # truthfully.  No deadline = no timer = byte-identical runs.
        deadline_timer = None
        if cfg.deadline_s:
            import threading as _threading
            deadline_timer = _threading.Timer(
                cfg.deadline_s, drain_cm.request,
                args=(f"deadline_exceeded: --deadline-s="
                      f"{cfg.deadline_s:g} budget spent",))
            deadline_timer.daemon = True
            deadline_timer.start()
        try:
            with device_trace(cfg.profile_dir, stderr), \
                    drain_cm as drain:
                with obs.span("run", device=cfg.device), \
                        _lane_device_scope(cfg, warm, stderr):
                    rc = _main_loop(cfg, inf, freport, fmsa, fsummary,
                                    summary, qfasta, stdout, stderr,
                                    cons_outs, resume_skip=resume_skip,
                                    resume_state=resume_state,
                                    drain=drain, warm=warm, obs=obs)
        finally:
            if deadline_timer is not None:
                deadline_timer.cancel()
        if rc == 0 and cache_store is not None:
            if cache_delta is not None:
                # the delta run is done: stamp the stats file
                # truthfully (cache_delta:true with computed-vs-served
                # record counts) and account the serve FRACTIONALLY
                _cache_delta_finish(cache_store, cfg.stats_path,
                                    cache_delta)
            if follow_cls is not None:
                # a cleanly idle-ended --follow run is a one-shot run
                # over the file's final bytes: populate under the
                # follow-less key so the NEXT restart delta-hits (or
                # exact-hits an unchanged file).  A rotation voided
                # the ride-along digest — the stream no longer equals
                # any one file's bytes — and blocks the insert.
                if getattr(inf, "consumed", False) \
                        and inf.hexdigest() is not None:
                    from pwasm_tpu.service.cache import \
                        derive_key as _derive_key
                    fkey = _derive_key(follow_cls,
                                       input_digest=inf.hexdigest())
                    if fkey is not None:
                        _cache_populate(cache_store, fkey, follow_cls,
                                        inf, cfg.stats_path, stderr)
            else:
                # populate on the way out: the COMPLETED run's output
                # files become the entry an identical later job serves.
                # The ingest reader's ride-along digest re-derives the
                # key (no second input read) AND proves the input did
                # not change between keying and running — a drifted
                # key means someone rewrote the input mid-run, and
                # inserting under the old key would poison every
                # future hit.
                _cache_populate(cache_store, cache_key_hex, cache_cls,
                                inf, cfg.stats_path, stderr)
        return rc
    except PwasmError as e:
        stderr.write(str(e))
        if obs is not None and obs.enabled:
            # failed runs terminate their timeline too — an operator
            # joining on run_finish must not see a crashed run as
            # still-running forever
            obs.event("run_finish", rc=e.exit_code,
                      error=str(e).strip()[:200])
        return e.exit_code
    finally:
        if obs is not None and obs.enabled:
            # a job's drain outlives the run inside a warm daemon:
            # un-bind the (about-to-close) event log first
            from pwasm_tpu.obs import NULL_OBS
            try:
                drain_cm.obs = NULL_OBS
            except NameError:
                pass
            obs.close(stderr)
        if inf is not sys.stdin:
            inf.close()
        for fo in opened:
            try:
                fo.close()   # no-op when the normal path closed it
            except Exception:
                pass


def _serve_cache_hit(manifest: dict, opts: dict, stderr,
                     verbose: bool = False) -> int:
    """Finish a cold-run cache hit: the output files are already
    written from the verified blobs — emit the hit-shaped ``--stats``
    (original run's numbers, ``cache_hit: true``, backend zeroed:
    THIS serve paid no probe) and return 0."""
    from pwasm_tpu.service.cache import write_hit_stats
    if "stats" in opts and opts["stats"] is not True:
        try:
            write_hit_stats(manifest, str(opts["stats"]), strict=True)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['stats']} for writing!\n")
    if verbose:
        print("pwasm: result served from cache (byte-identical to a "
              "full run of these inputs+flags)", file=stderr)
    return 0


def _cache_delta_serve(store, cls, opts: dict, stderr,
                       allow_equal: bool = False,
                       verbose: bool = False
                       ) -> tuple[int, int] | None:
    """Near-miss delta serve (ISSUE 17a): on an exact-key miss, look
    for a same-FAMILY entry whose recorded input is a per-line prefix
    of this job's input.  When one exists, its CRC-verified report
    bytes are written to this job's report path and the run is
    re-armed as a ``--resume`` over them — the existing resume
    machinery then drops the last cached record (its rows could not
    be proven whole by a header alone) and fast-forwards the rest as
    a parse-only skip, so only that record and the appended tail pay
    compute.  Byte parity with a cold run holds because the served
    prefix IS a completed run's bytes over the same prefix lines.
    Returns ``(records_served, records_total)`` or None (plain
    miss)."""
    from pwasm_tpu.service.cache import (delta_eligible, derive_keys,
                                         paf_line_digests)
    if cls is None or not delta_eligible(cls):
        return None
    digests, _fdig = paf_line_digests(cls.input_path)
    if not digests or len(digests) < 2:
        return None
    derived = derive_keys(cls)
    if derived is None:
        return None
    hit = store.delta_lookup(derived[1], digests,
                             allow_equal=allow_equal)
    if hit is None:
        return None
    _key, _manifest, blobs, nl = hit
    report_path = cls.output_paths["o"]
    try:
        with open(report_path, "wb") as f:
            f.write(blobs["o"])
    except OSError:
        return None     # unwritable output: the real run reports the
        #                 canonical "Cannot open file ..." diagnostic
    # a stale checkpoint left by an unrelated earlier run on this
    # report path would hijack the ckpt-first resume; the header-scan
    # heuristic over the just-served prefix is the resume we want
    _unlink_checkpoint(report_path)
    opts["resume"] = True
    if verbose:
        print(f"pwasm: cache delta hit — {nl} of {len(digests)} "
              "input records served from a cached prefix; computing "
              "the tail", file=stderr)
    # the resume header-scan re-pays the LAST cached record (nl - 1
    # records actually skip); the total is the input's record count
    return max(0, nl - 1), len(digests)


def _cache_delta_finish(store, stats_path: str | None,
                        served_total: tuple[int, int]) -> None:
    """Close out a completed delta run: fold the fractional outcome
    into the store's accounting and stamp the ``--stats`` artifact
    truthfully — ``cache_delta: true`` with the computed-vs-served
    record counts, never the hit-shaped ``cache_hit`` (this run DID
    probe and compute its tail)."""
    served, total = served_total
    store.note_delta(served, total)
    if not stats_path:
        return
    import json as _json
    try:
        with open(stats_path) as f:
            st = _json.load(f)
    except (OSError, ValueError):
        return
    if not isinstance(st, dict):
        return
    st["cache_delta"] = True
    st["cache_records_served"] = int(served)
    st["cache_records_total"] = int(total)
    try:
        with open(stats_path, "w") as f:
            _json.dump(st, f, indent=1)
            f.write("\n")
    except OSError:
        pass


def _cache_populate(store, key_hex: str | None, cls, inf,
                    stats_path: str | None, stderr) -> None:
    """Insert a completed run's outputs into the result cache (best
    effort — a failed insert costs the cache, never the job).  The
    shared ``insert_from_paths`` re-derives the key with the ingest
    reader's ride-along digest when one exists (else a fresh digest
    pass) and skips on drift — one populate implementation with the
    daemon tier."""
    if key_hex is None or cls is None:
        return
    from pwasm_tpu.service.cache import insert_from_paths
    input_digest = None
    if getattr(inf, "consumed", False):
        input_digest = inf.hexdigest()
    stats = None
    if stats_path:
        import json as _json
        try:
            with open(stats_path) as f:
                stats = _json.load(f)
        except (OSError, ValueError):
            stats = None
    if isinstance(stats, dict):
        # the entry's stats describe the RESULT, not how this run got
        # it: a future hit served from a delta-produced entry paid no
        # delta itself
        for k in ("cache_delta", "cache_records_served",
                  "cache_records_total"):
            stats.pop(k, None)
    insert_from_paths(store, key_hex, cls,
                      input_digest=input_digest, stats=stats)


def _lane_devices(warm):
    """The device-index span ``[lo, hi)`` of the job's device lease,
    or None for a cold run / single-lane daemon (the daemon only
    exposes the span when it actually runs multiple lanes, so classic
    serving is untouched)."""
    return getattr(warm, "lease_devices", None) \
        if warm is not None else None


def _lane_device_pool(span, stderr=None, warn: bool = True):
    """Map a lease's device-index span onto live jax devices (callable
    only after the backend probe passed).  Clamps when fewer devices
    exist than the lane layout assumes — on the single-CPU test
    backend every lane degrades to device 0 and the lease is a plain
    concurrency token (bytes are placement-independent).  On a REAL
    multi-device backend a clamp means the daemon's lane layout
    (lanes x devices-per-job) oversubscribes the inventory and
    'disjoint' lanes now overlap on a chip, so it is warned, not
    silent — the operator sized the lanes wrong.  ``warn=False`` for
    a rebuild of a pool the run already warned about (the shard-mesh
    site, inside ``_lane_device_scope``)."""
    import jax

    devs = jax.devices()
    lo, hi = span
    pool = devs[lo:hi]
    clamped = len(pool) < hi - lo
    if not pool:
        pool = [devs[lo % len(devs)]]
    if warn and clamped and len(devs) > 1:
        print(f"Warning: device lease [{lo},{hi}) exceeds the "
              f"{len(devs)}-device inventory — lane layout "
              "oversubscribes the mesh and lanes may share a chip; "
              "size --lanes*--devices-per-job to the real device "
              "count", file=stderr if stderr is not None
              else sys.stderr)
    return pool


@contextmanager
def _lane_device_scope(cfg, warm, stderr=None):
    """Pin a leased job's default device placement to its lane
    (ISSUE 8): two jobs holding different leases place their programs
    on disjoint chips instead of both landing on ``jax.devices()[0]``.
    ``jax.default_device`` is thread-local, so the daemon's concurrent
    worker threads scope independently.  Inert for cold runs, host
    jobs, and single-lane daemons; guarded by the same bounded backend
    probe as the main loop (never the first unprotected jax touch).
    ``stderr`` is the JOB's stderr (a served job's is a capture buffer
    the submitter reads — the oversubscription warning must land
    there, not on the daemon's global sys.stderr); the scope is the
    ONE place that warns, so the shard-mesh rebuild of the same pool
    below stays silent."""
    span = _lane_devices(warm)
    if span is None or cfg.device != "tpu":
        yield
        return
    from pwasm_tpu.utils.backend import device_backend_reachable
    ok, _why = device_backend_reachable()
    if not ok:
        yield      # the loop's own gate demotes to cpu right after
        return
    import jax

    with jax.default_device(_lane_device_pool(span, stderr)[0]):
        yield


def _native_msa_outputs(nmsa, cfg, fmsa, cons_outs, stderr,
                        device: bool = False, mesh=None,
                        stats=None, supervisor=None) -> None:
    """End-of-run MSA outputs through the delegated native engine — the
    exact twin of the Python-engine block in _main_loop (debug layout,
    unrefined -w, then refine-once + ace/info/cons).  With ``device``
    the consensus counts+votes come from the TPU kernel over the
    engine-rendered pileup (the north-star flow with the native merge):
    geometry-only build in C++, one device launch, votes applied back
    in C++ — bit-exact either way, so a kernel failure demotes to the
    host vote over the same rendered pileup (counted)."""
    import os
    import tempfile

    built = nmsa.count() > 0
    if cfg.debug and built:
        print(f">MSA ({nmsa.count()})", file=stderr)
        fd, tmp = tempfile.mkstemp(prefix="pwasm_layout_")
        os.close(fd)
        try:
            nmsa.write("layout", tmp)
            with open(tmp) as f:
                stderr.write(f.read())
        finally:
            os.unlink(tmp)
    if fmsa is not None:
        path = fmsa.name
        fmsa.close()
        if built:
            nmsa.write("mfa", path)
    if cons_outs and built:
        if device:
            import numpy as np

            nmsa.prepare_device()
            depth, length = nmsa.dims()
            mat = np.empty((depth, length), dtype=np.int8)
            nmsa.render_pileup(mat)

            def host_vote():
                # TPU→CPU degradation over the SAME rendered pileup —
                # bit-exact by the kernel/host vote parity contract
                if stats is not None:
                    stats.engine_fallbacks += 1
                from pwasm_tpu.native import consensus_vote_counts
                from pwasm_tpu.ops.consensus_host import \
                    host_class_counts
                counts = host_class_counts(mat)
                layers = counts.sum(axis=1, dtype=np.int32)
                chars = consensus_vote_counts(counts, layers)
                if chars is None:  # native lib vanished mid-run: cannot
                    raise PwasmError(  # happen while nmsa is live
                        "native consensus vote unavailable\n")
                return chars, counts

            def device_vote():
                from pwasm_tpu.align.msa import device_counts_votes
                return device_counts_votes(mat, mesh=mesh)

            if supervisor is not None:
                # supervised: retries + pileup-count-conservation
                # guardrail before the host demotion
                from pwasm_tpu.resilience.guardrails import \
                    check_consensus
                chars, counts = supervisor.run(
                    "consensus", device_vote,
                    validate=lambda r: check_consensus(r[0], r[1], mat),
                    fallback=host_vote)
            else:
                try:
                    chars, counts = device_vote()
                except Exception as e:  # backend down: host replay
                    from pwasm_tpu.utils import exc_detail
                    print("pwasm: device consensus fell back to host "
                          f"({exc_detail(e)})", file=stderr)
                    chars, counts = host_vote()
            nmsa.refine_external(counts, chars, cfg.remove_cons_gaps,
                                 cfg.refine_clipping)
        else:
            nmsa.refine(cfg.remove_cons_gaps, cfg.refine_clipping)
        contig = nmsa.contig()
        for kind in ("ace", "info", "cons"):
            if kind in cons_outs:
                f = cons_outs[kind]
                path = f.name
                f.close()
                nmsa.write(kind, path, contig, cfg.remove_cons_gaps,
                           cfg.refine_clipping)
    nmsa.close()


def _main_loop(cfg: Config, inf, freport, fmsa, fsummary, summary,
               qfasta: FastaFile, stdout, stderr,
               cons_outs: dict | None = None,
               resume_skip: int = 0,
               resume_state: dict | None = None, drain=None,
               warm=None, obs=None) -> int:
    """The per-PAF-line loop (pafreport.cpp:296-460)."""
    from pwasm_tpu.align.gapseq import FLAG_IS_REF, GapSeq
    from pwasm_tpu.align.msa import Msa
    from pwasm_tpu.obs import NULL_OBS
    from pwasm_tpu.utils import RunStats

    obs = obs if obs is not None else NULL_OBS
    stats = RunStats()

    # streaming inputs (FollowReader / StreamFeed) block between
    # records: hand them the drain flag so a SIGTERM (or the daemon's
    # per-job drain) wakes the wait and stops iteration at the current
    # record boundary — the loop below then takes its standard
    # preempted path (final ckpt, exit 75, resumable)
    if drain is not None and hasattr(inf, "bind_drain"):
        inf.bind_drain(drain)

    # one supervisor per run: every device round-trip (report batches,
    # --realign dispatches, the consensus/refine launches) goes through
    # it — bounded retries, per-batch deadline, circuit breaker, and
    # the --fallback degradation policy (pwasm_tpu.resilience)
    from pwasm_tpu.resilience import BatchSupervisor, ResiliencePolicy
    from pwasm_tpu.resilience.faults import parse_fault_spec, plan_from_env
    fault_plan = parse_fault_spec(cfg.inject_faults) \
        if cfg.inject_faults else plan_from_env()
    if fault_plan is not None:
        print(f"pwasm: fault injection armed (debug): {fault_plan}",
              file=stderr)
        if fault_plan.preempt and drain is not None:
            # the scripted preemption (preempt=N) pulls the SAME drain
            # flag a real SIGTERM sets — one code path, two triggers
            fault_plan.on_preempt = drain.request
    # --recover=auto (default): an open global breaker is re-probed on
    # a capped-exponential schedule and RECLOSES after consecutive
    # healthy probes — subsequent batches go back to the device
    # (mid-run re-promotion).  --recover=off keeps PR-1's terminal
    # breaker.
    monitor = None
    if cfg.recover == "auto":
        from pwasm_tpu.resilience.health import BackendHealthMonitor
        if warm is not None and warm.monitor is not None:
            # the warm serve process owns ONE monitor for its whole
            # life: job N+1 inherits job N's probe schedule and
            # open/half-open/closed state, re-bound to this job's
            # stats sink (the first job's --reprobe-* knobs win)
            monitor = warm.monitor.attach(stats=stats, stderr=stderr,
                                          obs=obs)
        else:
            monitor = BackendHealthMonitor(
                interval_s=cfg.reprobe_interval,
                max_interval_s=cfg.reprobe_max, stats=stats,
                stderr=stderr, obs=obs)
            if warm is not None:
                warm.monitor = monitor
    supervisor = BatchSupervisor(
        ResiliencePolicy(max_retries=cfg.max_retries,
                         deadline_s=cfg.device_deadline or None,
                         fallback=cfg.fallback),
        stats=stats, stderr=stderr, faults=fault_plan, monitor=monitor,
        obs=obs)
    if warm is not None and warm.supervisor_state:
        # a warm serve process: inherit the previous job's breaker /
        # site-trip / bucket-ceiling end state — a flap that opened
        # the breaker in job N must not be re-discovered (and re-paid)
        # by job N+1, and a reclose re-promotes every subsequent job
        supervisor.restore_state(warm.supervisor_state)
    if resume_state is not None:
        # a --resume inherits the killed run's breaker/monitor/fault
        # state: a run killed mid-outage must not re-trip (or worse,
        # re-attempt a dead backend), and a scripted down= window
        # continues at the supervised call it stopped at.  Restored
        # AFTER any warm-service state on purpose: the job's own ckpt
        # is the more specific fact (it carries the fault clock a
        # scripted window needs; warm state never does)
        supervisor.restore_state(resume_state)

    alnpairs: dict[str, int] = {}   # gene-mode (query~target) dedup counts
    ref_cache: dict[str, bytes] = {}
    refseq_id: str | None = None
    refseq: bytes | None = None
    refseq_rc: bytes | None = None
    ref_gseq: GapSeq | None = None  # MSA instance of the current refseq
    ref_msa: Msa | None = None
    numalns = 0

    # --device=tpu: buffer alignments and flush through one batched device
    # program per cfg.batch (the SURVEY.md §3.1 TPU boundary — control
    # crosses host->device once per batch, not per alignment)
    use_device = cfg.device != "cpu"
    if use_device:
        # bounded health check before the first jax touch: an
        # unreachable tunnel must cost seconds and a loud CPU demotion,
        # not an indefinite hang at backend init (SURVEY.md §5 failure
        # detection; PWASM_DEVICE_PROBE=0 skips)
        from pwasm_tpu.utils import backend as _backend
        from pwasm_tpu.utils.backend import device_backend_reachable
        # per-run probe accounting (the warm-pool reuse gate): diff the
        # process-wide counters around the gate so the job's --stats
        # says whether it PAID a subprocess probe or answered from the
        # warm process state (backend.probes / backend.warm_hits)
        _p0 = _backend.probe_counters["probes"]
        _w0 = _backend.probe_counters["warm_hits"]
        ok, why = device_backend_reachable()
        stats.backend_probes += \
            _backend.probe_counters["probes"] - _p0
        stats.backend_warm_hits += \
            _backend.probe_counters["warm_hits"] - _w0
        if not ok:
            print(f"Warning: jax backend unreachable ({why.strip()}); "
                  "running with --device=cpu", file=stderr)
            use_device = False
            cfg.device = "cpu"
            cfg.shard = 0
            stats.engine_fallbacks += 1
        else:
            # repeated pafreport invocations are the reference's
            # workflow: persist compiled programs across runs so only
            # the first invocation pays the device compiles.  An
            # explicit --compile-cache-dir (or the serve daemon's
            # warm-context dir — the fleet-member restart lever,
            # ROADMAP item 2b) overrides the env/default location.
            from pwasm_tpu.ops import enable_compilation_cache
            enable_compilation_cache(
                cfg.compile_cache_dir
                or (getattr(warm, "compile_cache_dir", None)
                    if warm is not None else None))
    pending: list[tuple] = []
    cons_outs = cons_outs or {}
    build_msa_out = fmsa is not None or bool(cons_outs)

    # MSA builds delegate the progressive merge + writers to the native
    # C++ engine the package already ships (~8x faster per member than
    # the Python engine; byte-identical by the standalone binary's
    # parity contract — VERDICT r3 item 5).  On --device=tpu the engine
    # renders the pileup for the device consensus kernel and applies
    # its votes (the north-star flow with the native merge).
    # PWASM_NATIVE_MSA=0 opts out (and the parity tests use it).
    nmsa = None
    nmsa_batch = False
    if build_msa_out:
        import os as _os

        from pwasm_tpu.native import native_msa
        nmsa = native_msa(stream=stderr)
        if nmsa is None \
                and _os.environ.get("PWASM_NATIVE_MSA", "1") != "0" \
                and _os.environ.get("PWASM_NATIVE", "1") != "0":
            # no toolchain / failed native build: the Python engine is
            # bit-exact but ~8x slower per merge — surface the demotion
            # like every other engine-level fallback
            print("pwasm: native MSA engine unavailable; using the "
                  "Python engine", file=stderr)
            stats.engine_fallbacks += 1
        # batched add marshalling (ROADMAP item 2 lever a): buffer the
        # per-alignment native inserts and marshal a whole flush in ONE
        # ffi crossing (pw_msa_add_batch).  PWASM_NATIVE_MSA_BATCH=0 is
        # the per-alignment A/B hatch (mirrors PWASM_HOST_FORMAT /
        # PWASM_HOST_COLUMNAR: regressions stay bisectable).
        nmsa_batch = nmsa is not None and _os.environ.get(
            "PWASM_NATIVE_MSA_BATCH", "1") != "0"
    # (al_key, tlabel, realigned, refseq_b, add_batch item) rows
    # awaiting the next batched native merge; keys mirror the buffered
    # pair slots so the gene-mode dedup logic can force a flush when it
    # needs a pending pair's verdict (a dropped insert frees its slot)
    msa_pending: list[tuple] = []
    msa_pending_keys: set[str] = set()

    # --shard: one mesh for the whole run (device work spreads over it;
    # consensus counts psum over its depth axis).  Built lazily so a
    # plain run never initializes jax.  A job holding a multi-device
    # lease (ISSUE 8) shards over EXACTLY its lane's devices — the
    # ICI-sharded big-batch path with the psum'd consensus counts
    # stays inside the lease, never touching a neighbor job's chips.
    shard_mesh = None
    if use_device and cfg.shard:
        import jax

        from pwasm_tpu.parallel.mesh import make_mesh
        span = _lane_devices(warm)
        pool = _lane_device_pool(span, stderr, warn=False) \
            if span is not None else jax.devices()
        n_dev = len(pool)
        want = n_dev if cfg.shard < 0 else cfg.shard
        if want > n_dev:
            where = f"the job's device lease holds {n_dev}" \
                if span is not None else f"only {n_dev} devices are " \
                "visible"
            raise PwasmError(
                f"Error: --shard={want} but {where}!\n")
        shard_mesh = make_mesh(want, devices=pool)
        if cfg.verbose:
            print(f"sharding over mesh {dict(shard_mesh.shape)}",
                  file=stderr)

    inflight: list = []   # submitted-but-unformatted batches (<= 2)

    # host stage pipeline (ISSUE 7): the host report engine mirrors the
    # device path's two-deep in-flight flush pipeline — ONE worker
    # thread runs batch k's columnar analysis + block formatting while
    # the main thread parses/extracts batch k+1 and merges the MSA.
    # The native extraction (ctypes) and the large numpy analysis ops
    # release the GIL, so the stages genuinely overlap.
    # PWASM_HOST_PIPELINE=0 degrades to the synchronous path (the
    # bisect hatch; byte parity either way by construction — finish
    # closures write in submit order).
    host_pool = None
    host_pool_owned = False
    if not use_device and freport is not None:
        import os as _os
        if _os.environ.get("PWASM_HOST_PIPELINE", "1") != "0" \
                and _os.environ.get("PWASM_HOST_COLUMNAR", "1") != "0":
            # (the scalar-engine hatch never submits to the pool —
            # don't spawn an idle worker for its A/B arm)
            if warm is not None and hasattr(warm, "host_executor"):
                # warm-serve: the daemon's ONE persistent pipeline
                # worker (and its thread-local FormatBuffers scratch,
                # report/rowbytes.py) is shared across consecutive
                # jobs — no per-job thread spawn or buffer allocation
                # spike in the daemon
                host_pool = warm.host_executor()
            else:
                from concurrent.futures import ThreadPoolExecutor
                host_pool = ThreadPoolExecutor(
                    1, thread_name_prefix="pwasm-hostpipe")
                host_pool_owned = True

    # batch-granular durability (SURVEY.md §5 checkpoint/resume): after
    # each completed batch the report prefix is fsynced and its
    # (bytes, records) recorded atomically in <report>.ckpt, so a
    # killed run resumes at the last completed batch.  Both report
    # engines flush in batches now, so the CPU path gets the same
    # durability the device path shipped in PR 1 (previously it could
    # only header-scan resume).  Records already in the file from a
    # --resume count toward the total.
    report_path = getattr(freport, "name", None) \
        if freport not in (stdout, None) else None
    emitted = [resume_skip]

    # per-flush host-stage folding (ISSUE 11 satellite): the --stats
    # host block used to reach pwasm_host_stage_seconds_total only at
    # end of run — a drifting canary (realistic_host_report_1k_s) had
    # no live per-stage attribution.  Each completed batch now folds
    # the stage DELTAS into the live counter and the flight record;
    # the end-of-run fold applies only the residual, so totals match
    # the --stats JSON exactly (no double count).
    host_folded = {"parse": 0.0, "extract": 0.0, "analyze": 0.0,
                   "format": 0.0}

    def fold_host_stages() -> None:
        cur = {"parse": stats.host_parse_s,
               "extract": stats.host_extract_s,
               "analyze": stats.host_analyze_s,
               "format": stats.host_format_s}
        for k, v in cur.items():
            d = v - host_folded[k]
            if d > 0:
                obs.count("host_stage_seconds", d, stage=k)
                if obs.flight is not None:
                    obs.flight.note("host_" + k, d)
            host_folded[k] = v

    def note_batch_done(nrecords: int) -> None:
        emitted[0] += nrecords
        fold_host_stages()
        if report_path is not None:
            if _write_checkpoint(freport, report_path, emitted[0],
                                 supervisor.export_state()):
                stats.res_checkpoints += 1
                obs.event("ckpt_write", records=emitted[0],
                          batch=nrecords)

    def _drop_msa(key: str, tlabel: str, realigned: bool) -> None:
        # NB the alignment's report rows were already emitted — it
        # is only excluded from the MSA, so it counts under
        # msa_dropped, not skipped_bad_lines; the freed dedup slot
        # lets a later valid alignment of the pair take its place
        stats.msa_dropped += 1
        src = ("re-aligned gap structure — possible re-aligner "
               "defect" if realigned else "out-of-layout gap "
               "structure in the input")
        print(f"Warning: excluding alignment {tlabel} from the MSA "
              f"({src})", file=stderr)
        alnpairs.pop(key, None)

    def flush_msa_pending() -> None:
        """Merge the buffered alignments into the native MSA through
        ONE ``pw_msa_add_batch`` crossing.  Every buffered item shares
        the current query (the buffer flushes on query change), so
        rid/refseq/r_len marshal once; per-item failures keep the
        sequential semantics — the engine stops at the failing item
        and the drop hook below either raises (the fatal
        non-``--skip-bad-lines`` path) or replays the per-alignment
        drop bookkeeping in input order.

        Parity contract vs the ``PWASM_NATIVE_MSA_BATCH=0`` per-item
        hatch: byte-identical OUTPUT FILES (report/-w/-s) on every run
        that completes (clean corpora and ``--skip-bad-lines`` drops).
        stderr is ordering-equivalent, not byte-equivalent: a drop
        warning surfaces at this flush boundary, so it can land after
        later lines' warnings that per-item mode would print after it.
        On the fatal path the error itself is identical (same
        PwasmError, same rc) but also surfaces at the flush boundary
        instead of mid-input, so alignments buffered AFTER the failing
        one may already have report rows/warnings out when the run
        aborts — inherent to batching a failure only the native engine
        can detect, and moot for the aborted run's (invalid) partial
        output."""
        if not msa_pending:
            return
        items, msa_pending[:] = msa_pending[:], []
        msa_pending_keys.clear()
        rid, r_len = items[0][0]
        refseq_b = items[0][3]

        def on_drop(idx: int, msg: str) -> None:
            if not cfg.skip_bad_lines:
                raise PwasmError(msg)
            _key, tlab, realig = (items[idx][1], items[idx][2],
                                  items[idx][4])
            _drop_msa(_key, tlab, realig)

        nmsa.add_batch(rid, refseq_b, r_len,
                       [it[5] for it in items], on_drop)

    def msa_add(aln, tlabel: str, refseq_b: bytes, ord_num: int,
                realigned: bool = False) -> None:
        """Insert one alignment into the progressive MSA (the per-line
        body of pafreport.cpp:394-421)."""
        nonlocal ref_gseq, ref_msa
        al = aln.alninfo

        def drop_from_msa():
            _drop_msa(f"{al.r_id}~{al.t_id}", tlabel, realigned)

        if nmsa is not None:
            if nmsa_batch:
                key = f"{al.r_id}~{al.t_id}"
                msa_pending.append(
                    ((al.r_id, al.r_len), key, tlabel, refseq_b,
                     realigned,
                     (tlabel, bytes(aln.tseq), al.r_alnstart,
                      aln.reverse, aln.rgaps, aln.tgaps, ord_num)))
                msa_pending_keys.add(key)
                if len(msa_pending) >= cfg.batch:
                    flush_msa_pending()
                return
            ok = nmsa.add(tlabel, bytes(aln.tseq), al.r_alnstart,
                          aln.reverse, al.r_id, refseq_b, al.r_len,
                          aln.rgaps, aln.tgaps, ord_num)
            if not ok:
                if not cfg.skip_bad_lines:
                    raise PwasmError(nmsa.gap_err)
                drop_from_msa()
            return
        taseq = GapSeq(tlabel, "", aln.tseq, offset=al.r_alnstart,
                       revcompl=aln.reverse)
        first_ref_aln = ref_gseq is None
        if first_ref_aln:
            rseq = GapSeq(al.r_id, "", refseq_b)
            rseq.set_flag(FLAG_IS_REF)
        else:
            # bare instance of refseq for this alignment
            rseq = GapSeq(al.r_id, "", b"", seqlen=al.r_len)
        # once a gap, always a gap: propagate this alignment's gaps.
        # rseq/taseq are fresh objects, so a gap the layout cannot hold
        # (e.g. an alignment starting with a deletion on the reverse
        # strand puts a ref gap at position r_len — fatal in the
        # reference's setGap too, GapAssem.cpp:105-107) fails BEFORE any
        # MSA mutation and is skippable under --skip-bad-lines
        try:
            for g in aln.rgaps:
                rseq.set_gap(g.pos, g.len)
            for g in aln.tgaps:
                taseq.set_gap(g.pos, g.len)
        except PwasmError:
            if not cfg.skip_bad_lines:
                raise
            drop_from_msa()
            return
        newmsa = Msa(rseq, taseq)
        if first_ref_aln:
            newmsa.ordnum = ord_num
            ref_msa = newmsa
            ref_gseq = rseq
        else:
            ref_gseq.msa.add_align(ref_gseq, newmsa, rseq)
            ref_msa = ref_gseq.msa

    # --realign: buffer MSA insertions and re-align each buffered target
    # with the batched banded-DP traceback (ops/realign.py), replacing
    # the PAF's gap structure before the progressive merge.  Insertion
    # order is preserved, so the resulting MSA differs only in the gap
    # structures the DP improved.
    re_pending: list[tuple] = []

    def flush_realign() -> None:
        if not re_pending:
            return
        if cfg.device == "cpu":
            # --device=cpu must never touch a (possibly unhealthy) TPU
            # backend: pin the jax platform before the first backend init.
            # A no-op once a backend is up (update raises; ignore).
            import jax
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        from pwasm_tpu.ops.realign import ops_to_gaps, realign_pairs
        items, re_pending[:] = re_pending[:], []
        results = realign_pairs(
            [(q_seg, bytes(aln.tseq)) for aln, _t, _r, _o, q_seg in items],
            band=cfg.band, mesh=shard_mesh, supervisor=supervisor)
        for (aln, tlabel, refseq_b, ordn, _q), res in zip(items, results):
            al = aln.alninfo
            if res is None:  # outside realignment resource bounds:
                # keep the PAF's own gap structure for this alignment
                print(f"Warning: {al.r_id}~{al.t_id} not re-aligned "
                      "(no band up to the escalation ceiling covered "
                      "its optimal path, and it is too large for the "
                      "host oracle); keeping PAF gaps", file=stderr)
            else:
                _score, ops = res
                aln.rgaps, aln.tgaps = ops_to_gaps(
                    ops, aln.offset, al.r_len,
                    al.t_alnend - al.t_alnstart, aln.reverse)
                stats.realigned += 1
            msa_add(aln, tlabel, refseq_b, ordn,
                    realigned=res is not None)

    def flush_pending(drain: bool = False):
        """Flush the pending report batch.

        BOTH engines pipeline two-deep now.  Device path: submit the
        batch, then format the OLDEST in-flight batch — JAX dispatch is
        async, so batch k's device program runs while batches k-1/k-2
        are formatted and written.  Host path: batch k's columnar
        analysis + block assembly run on the host pipeline worker
        (report/columnar.py submit_diff_info_batch_host) while the main
        thread parses/extracts the next batch; finish closures write in
        submit order, so the report stays a clean prefix of input
        order.  ``drain`` formats every in-flight batch at end of
        input.  The host path never touches the device module: the
        plain-CPU CLI must not initialize (or even import) jax — a
        pinned-but-unhealthy TPU tunnel would hang or kill an otherwise
        host-only run."""
        if not pending and not inflight:
            return  # nothing buffered
        # take the batch first: if the flush itself raises, the finally
        # below must not retry it (the retry would mask the live error)
        batch, pending[:] = pending[:], []
        if not use_device and batch:
            import os as _os
            if _os.environ.get("PWASM_HOST_COLUMNAR", "1") == "0":
                # scalar per-alignment loop (the ground-truth engine):
                # the columnar path's escape hatch, and the bench's
                # same-process A/B reference — synchronous on purpose
                with obs.span("flush_host", n=len(batch)):
                    from pwasm_tpu.report.diff_report import \
                        print_diff_info
                    for aln, rlabel, tlabel, refseq in batch:
                        print_diff_info(
                            aln, rlabel, tlabel, freport, refseq,
                            skip_codan=cfg.skip_codan,
                            motifs=cfg.motifs, summary=summary)
                note_batch_done(len(batch))
                return
            from pwasm_tpu.report.columnar import \
                submit_diff_info_batch_host
            with obs.span("flush_submit", n=len(batch)):
                inflight.append((submit_diff_info_batch_host(
                    batch, freport, skip_codan=cfg.skip_codan,
                    motifs=cfg.motifs, summary=summary, stats=stats,
                    executor=host_pool), len(batch)))
        elif batch:
            from pwasm_tpu.report.device_report import \
                submit_diff_info_batch
            with obs.span("flush_submit", n=len(batch)):
                inflight.append((submit_diff_info_batch(
                    batch, freport, skip_codan=cfg.skip_codan,
                    motifs=cfg.motifs, summary=summary, stats=stats,
                    mesh=shard_mesh, supervisor=supervisor),
                    len(batch)))
            stats.device_batches += 1
        while len(inflight) > (0 if drain else 2):
            fin, nrec = inflight.pop(0)
            try:
                with obs.span("flush_format", n=nrec):
                    fin()
            except BaseException:
                # a formatting failure mid-batch must leave the report a
                # clean prefix of input order (--resume depends on it):
                # drop everything submitted after the failure point
                inflight.clear()
                raise
            note_batch_done(nrec)

    # Batched native extraction: buffer parsed records and cross into C
    # ONCE per flush (pw_extract_batch) instead of once per line — the
    # same stop-at-the-failing-item protocol and parity contract as
    # pw_msa_add_batch (byte-identical OUTPUT FILES; stderr is
    # ordering-equivalent at flush boundaries).
    # PWASM_NATIVE_EXTRACT_BATCH=0 is the per-item A/B hatch.
    # --skip-bad-lines keeps the per-item path: its recovery
    # bookkeeping (dedup-slot release, per-line skip warnings in input
    # position) is per-line by construction.
    ex_pending: list[tuple] = []
    use_ex_batch = False
    if not cfg.skip_bad_lines:
        import os as _os
        if (_os.environ.get("PWASM_NATIVE", "1") != "0"
                and _os.environ.get(
                    "PWASM_NATIVE_EXTRACT_BATCH", "1") != "0"):
            from pwasm_tpu.native import native_available
            use_ex_batch = native_available()

    def consume_aln(rec, aln, refseq_b: bytes, refseq_aln: bytes,
                    ordnum: int) -> None:
        """Post-extraction per-alignment body (stats, report row, MSA
        insert bookkeeping) — shared verbatim by the per-item and the
        batched extraction paths, so their outputs cannot drift."""
        al = rec.alninfo
        stats.alignments += 1
        stats.aligned_bases += al.t_alnend - al.t_alnstart
        stats.events += len(aln.tdiffs)
        tlabel = f"{al.t_id}:{al.t_alnstart}-{al.t_alnend}" \
            + ("-" if al.reverse else "+")
        rlabel = al.r_id
        if cfg.fullgenome:
            rlabel += f":{al.r_alnstart}-{al.r_alnend}"
        if freport is not None:
            if len(qfasta) == 1 and not cfg.fullgenome:
                rlabel = ""
            if stats.resumed_past < resume_skip:
                # --resume cursor: this alignment's rows are already
                # in the report from the interrupted run
                stats.resumed_past += 1
            else:
                # both engines batch: the device path submits one
                # fused program per flush, the host path runs one
                # vectorized columnar analysis per flush — and both
                # leave a durable checkpoint per completed batch
                pending.append((aln, rlabel, tlabel, refseq_b))
                if len(pending) >= cfg.batch:
                    flush_pending()
        if build_msa_out:
            if cfg.realign:
                q_seg = refseq_aln[aln.offset:
                                   aln.offset + (al.r_alnend -
                                                 al.r_alnstart)]
                re_pending.append((aln, tlabel, refseq_b, ordnum,
                                   q_seg))
                if len(re_pending) >= cfg.batch:
                    flush_realign()
            else:
                msa_add(aln, tlabel, refseq_b, ordnum)

    def flush_extract() -> None:
        """Extract the buffered records through ONE native crossing,
        then run each alignment's consume body in input order."""
        if not ex_pending:
            return
        from pwasm_tpu.native import extract_batch_native
        items, ex_pending[:] = ex_pending[:], []
        if len(items) == 1:
            # a one-record flush (--batch=1 streaming, lone query-
            # change tail) pays the single crossing either way; the
            # direct call skips the batch marshalling so streaming's
            # per-record latency keeps its floor
            rec, refseq_aln, refseq_b, ordnum = items[0]
            t_st = _pc()
            aln = extract_alignment(rec, refseq_aln)
            stats.host_extract_s += _pc() - t_st
            consume_aln(rec, aln, refseq_b, refseq_aln, ordnum)
            return
        t_st = _pc()
        alns, ex_err = extract_batch_native(
            [it[0] for it in items], [it[1] for it in items])
        stats.host_extract_s += _pc() - t_st
        if alns is None:   # lib lost after the gate probe: per-item
            for rec, refseq_aln, refseq_b, ordnum in items:
                t_st = _pc()
                aln = extract_alignment(rec, refseq_aln)
                stats.host_extract_s += _pc() - t_st
                consume_aln(rec, aln, refseq_b, refseq_aln, ordnum)
            return
        for aln, (rec, refseq_aln, refseq_b, ordnum) in zip(alns,
                                                            items):
            consume_aln(rec, aln, refseq_b, refseq_aln, ordnum)
        if ex_err is not None:
            # the failing item aborts the run exactly as per-item mode
            # would, after the rows of the items before it landed
            raise ex_err

    t_loop = obs.clock()   # the parse/extract/flush phase span
    # per-stage host walls (--stats "host" block): parse and extract
    # accumulate here on the main loop; analyze/format accumulate on
    # the pipeline worker (disjoint RunStats fields, so the threads
    # never tear each other's sums)
    from time import perf_counter as _pc
    try:
        file_line = 0
        for line in inf:
            if drain is not None and drain.requested:
                # graceful drain: stop consuming input at this batch
                # boundary — the finally below completes the in-flight
                # pipeline and checkpoints it, then the run exits
                # "preempted, resumable" (the next --resume continues
                # exactly here)
                break
            file_line += 1
            line = line.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            stats.lines += 1
            try:
                t_st = _pc()
                rec = parse_paf_line(line)
                stats.host_parse_s += _pc() - t_st
            except PwasmError:
                if not cfg.skip_bad_lines:
                    raise
                stats.skipped_bad += 1
                print(f"Warning: skipping malformed PAF line "
                      f"{file_line}", file=stderr)
                continue
            al: AlnInfo = rec.alninfo
            if al.r_id == al.t_id:
                stats.skipped_self += 1
                if cfg.verbose:
                    print("Skipping alignment of qry seq to itself.",
                          file=stderr)
                continue
            new_pair = None
            if not cfg.fullgenome:  # gene CDS mode: first q~t alignment only
                key = f"{al.r_id}~{al.t_id}"
                if key in msa_pending_keys:
                    # a buffered native insert of this pair may still
                    # be DROPPED (out-of-layout gaps free its dedup
                    # slot for this very line): resolve the batch
                    # before the dup verdict
                    flush_msa_pending()
                if key not in alnpairs:
                    alnpairs[key] = 0
                    new_pair = key
                else:
                    alnpairs[key] += 1
                    stats.skipped_dedup += 1
                    if alnpairs[key] == 1:
                        print(f"Warning: alignment {al.r_id} to {al.t_id} "
                              f"already seen, ignoring ", file=stderr)
                    continue
            numalns += 1
            if (freport is not None and not build_msa_out
                    and not cfg.skip_bad_lines
                    and stats.resumed_past < resume_skip):
                # --resume fast path: this alignment is already in the
                # report; advance the cursor on parse-level info alone
                # (no refseq fetch, no extraction), so resume cost scales
                # with the REMAINING work (SURVEY.md §5).  Disabled under
                # --skip-bad-lines: there a line can parse yet have been
                # skipped at extraction in the original run (absent from
                # the report), so cursor advance must go through
                # extraction — the slow path below — to stay in sync.
                stats.resumed_past += 1
                stats.alignments += 1
                stats.aligned_bases += al.t_alnend - al.t_alnstart
                continue
            if refseq_id is None or refseq_id != al.r_id:
                # buffered EXTRACTIONS may span queries (each record
                # carries its own ref pointer), but their downstream
                # MSA inserts may not: consume them first, THEN merge
                # the buffered re-alignments and native inserts before
                # the layout state resets (the add-batch buffer never
                # spans a query boundary)
                flush_extract()
                flush_realign()
                flush_msa_pending()
                if al.r_id in ref_cache:
                    refseq = ref_cache[al.r_id]
                else:
                    fetched = qfasta.fetch(al.r_id)
                    if fetched is None:
                        raise PwasmError(
                            f"Error: could not retrieve sequence for "
                            f"{al.r_id} !\n")
                    refseq = bytes(fetched).upper()
                    ref_cache[al.r_id] = refseq
                refseq_rc = revcomp(refseq)
                refseq_id = al.r_id
                ref_gseq = None
                if nmsa is not None:
                    nmsa.reset()  # a new query starts a new MSA
            if al.r_len != len(refseq):
                raise PwasmError(
                    f"Error: ref seq len in this PAF line ({al.r_len}) differs "
                    f"from loaded sequence length({len(refseq)})!\n{line}\n")
            refseq_aln = refseq_rc if al.reverse else refseq
            if use_ex_batch:
                # batched native extraction: this record crosses into
                # C with the rest of its flush; its consume body runs
                # at the flush boundary, still in input order
                ex_pending.append((rec, refseq_aln, refseq, numalns))
                if len(ex_pending) >= cfg.batch:
                    flush_extract()
                continue
            try:
                t_st = _pc()
                aln = extract_alignment(rec, refseq_aln)
                stats.host_extract_s += _pc() - t_st
            except PwasmError:
                if not cfg.skip_bad_lines:
                    raise
                numalns -= 1
                if new_pair is not None:
                    # a skipped line must not make later valid alignments
                    # of the same (q,t) pair look like duplicates
                    del alnpairs[new_pair]
                stats.skipped_bad += 1
                print(f"Warning: skipping malformed PAF line "
                      f"{file_line}", file=stderr)
                continue
            consume_aln(rec, aln, refseq, refseq_aln, numalns)
        # end of input (or a drain break): extract and consume the
        # buffered tail so its rows reach the report/MSA buffers the
        # finally below drains (and the drain checkpoint covers them)
        flush_extract()
    finally:
        # emit whatever the batch buffers hold — including when a later
        # bad line raises, so earlier alignments' rows aren't dropped:
        # records buffered for batched extraction are extracted and
        # consumed first (they preceded the failing line in input
        # order), then the report/device buffers drain even if one of
        # THOSE records fails extraction — then retire the host
        # pipeline worker if this run owns it (a warm-serve run
        # borrows the daemon's persistent worker and must leave it
        # running for the next job; the drain above already joined
        # every future this run submitted)
        try:
            try:
                flush_extract()
            finally:
                flush_pending(drain=True)
            obs.span_complete("input_loop", t_loop, lines=stats.lines,
                              alignments=stats.alignments)
        finally:
            if host_pool_owned:
                host_pool.shutdown(wait=True)

    # a drain requested during the final flushes still counts: the
    # in-flight batches completed (and checkpointed) above, but the
    # end-of-run MSA/consensus work is exactly the multi-second tail a
    # preemption deadline cannot afford — skip it, exit resumable, and
    # let the --resume run (which replays the MSA from the full input)
    # produce the complete outputs

    def _output_tail() -> None:
        if nmsa is not None:
            flush_realign()
            flush_msa_pending()
            _native_msa_outputs(nmsa, cfg, fmsa, cons_outs, stderr,
                                device=use_device, mesh=shard_mesh,
                                stats=stats, supervisor=supervisor)
            return
        flush_realign()
        if cfg.debug and ref_msa is not None:
            print(f">MSA ({ref_msa.count()})", file=stderr)
            ref_msa.print_layout(stderr, "v")
        if fmsa is not None and ref_msa is not None:
            ref_msa.write_msa(fmsa)
            fmsa.close()
        if cons_outs and ref_msa is not None:
            # consensus path (the library capability pafreport never
            # calls, SURVEY.md §2.3): refine once, then emit the
            # requested formats.  write_msa above already captured the
            # unrefined layout, so the reference's -w output is
            # unchanged by refinement side effects.
            ref_msa.finalize()
            ref_msa.refine_msa(remove_cons_gaps=cfg.remove_cons_gaps,
                               refine_clipping=cfg.refine_clipping,
                               device=use_device, mesh=shard_mesh,
                               supervisor=supervisor)
            contig = ref_msa.seqs[0].name if ref_msa.seqs else "contig"
            if "ace" in cons_outs:
                ref_msa.write_ace(cons_outs["ace"], contig)
            if "info" in cons_outs:
                ref_msa.write_info(cons_outs["info"], contig)
            if "cons" in cons_outs:
                ref_msa.write_cons(cons_outs["cons"], contig)
            stats.engine_fallbacks += ref_msa.engine_fallbacks

    preempted = drain is not None and drain.requested
    if not preempted:
        # the tail runs in the drain's INTERRUPTIBLE phase: past the
        # batch loop there is no next batch boundary to drain at, so a
        # signal landing mid-consensus aborts the phase (PreemptedError)
        # instead of being silently ignored until the model finishes —
        # the tail's outputs are rebuilt whole by --resume, so an
        # aborted tail loses nothing
        from contextlib import nullcontext

        from pwasm_tpu.resilience.lifecycle import PreemptedError
        try:
            with (drain.interrupting() if drain is not None
                  else nullcontext()), obs.span("msa_tail"):
                _output_tail()
        except PreemptedError:
            preempted = True
    if preempted and nmsa is not None:
        nmsa.close()   # no-op when the completed tail closed it
    for f in cons_outs.values():
        f.close()
    if fsummary is not None:
        # on a preempted run this is the PARTIAL summary of the batches
        # that completed before the drain — the --resume run rewrites
        # it (documented: a resumed -s covers the resumed portion)
        summary.write(fsummary)
        fsummary.close()
    if freport not in (stdout, None):
        freport.close()
    if report_path is not None and not preempted:
        # the run completed: the report is whole, so the mid-run
        # checkpoint is obsolete (a later --resume skips via the
        # header scan, which now sees only complete records).  A
        # PREEMPTED run keeps its checkpoint — it is the resume
        # contract the drain just paid for.
        _unlink_checkpoint(report_path)
    supervisor.finalize_stats()   # a run ENDING degraded still owes
    #                               its open window to degraded_wall_s
    if warm is not None:
        # hand the end-state breaker/ceiling snapshot to the warm
        # process for the NEXT job.  The fault clock is stripped:
        # scripted fault windows (--inject-faults) are a per-job
        # debug contract — one job's clock must never advance (or
        # disarm) another job's scripted windows.
        warm.supervisor_state = {
            k: v for k, v in supervisor.export_state().items()
            if k != "fault_calls"}
    stats.preempted = preempted
    if obs.registry is not None:
        # the metrics surface is a pure function of the SAME versioned
        # --stats schema (obs/catalog.py): fold the finished run in and
        # stamp the breaker-state gauge; run()'s close publishes the
        # textfile atomically
        from pwasm_tpu.obs.catalog import (breaker_state_value,
                                           fold_run_stats)
        d = stats.as_dict()
        # the per-flush folds above already attributed most of the
        # host block: fold only the residual so the counter total
        # equals the --stats JSON exactly
        d["host"] = {k + "_s": round(max(
            0.0, d["host"][k + "_s"] - host_folded[k]), 6)
            for k in host_folded}
        fold_run_stats(obs.run_metrics, d)
        obs.set_gauge("breaker_state", breaker_state_value(
            supervisor.breaker_open,
            monitor.state if monitor is not None else None))
    if cfg.stats_path:
        try:
            with open(cfg.stats_path, "w") as f:
                stats.write(f)
        except OSError:
            raise PwasmError(
                f"Cannot open file {cfg.stats_path} for writing!\n")
    if stats.fallback_batches:
        # a degraded --device=tpu run must be visible at exit, not just
        # in the once-per-run warning scrolled past hours earlier
        print(f"Warning: {stats.fallback_batches}/{stats.device_batches} "
              "device batches fell back to the host scalar path",
              file=stderr)
    if stats.engine_fallbacks:
        print(f"Warning: {stats.engine_fallbacks} engine/device stage(s) "
              "fell back from the requested device/native path",
              file=stderr)
    if supervisor.breaker_open:
        # ending degraded must be visible at exit, not only in a
        # breaker-open line scrolled past hours earlier
        print("Warning: run ended with the circuit breaker OPEN "
              f"({stats.res_degraded_batches} batch(es) degraded to "
              f"the host, {stats.res_degraded_wall_s:.1f}s degraded "
              "wall)", file=stderr)
    if cfg.verbose:
        print(stats.brief(), file=stderr)
    if preempted:
        from pwasm_tpu.core.errors import EXIT_PREEMPTED
        done = f"{emitted[0]} record(s) durable" if report_path \
            else "no -o report (nothing checkpointed)"
        print(f"pwasm: preempted ({drain.reason}) — drained cleanly, "
              f"{done}; rerun with --resume to complete "
              f"(exit {EXIT_PREEMPTED})", file=stderr)
        obs.event("run_finish", rc=EXIT_PREEMPTED, preempted=True,
                  reason=drain.reason, records=emitted[0],
                  alignments=stats.alignments)
        return EXIT_PREEMPTED
    obs.event("run_finish", rc=0, preempted=False,
              alignments=stats.alignments, events=stats.events,
              wall_s=round(stats.wall_s, 3))
    return 0


def main() -> None:
    try:
        rc = run(sys.argv[1:])
    except PwasmError as e:
        sys.stderr.write(str(e))
        rc = e.exit_code
    except BrokenPipeError:
        # downstream consumer (e.g. `head`) closed the pipe; exit quietly
        # like the reference binary does on SIGPIPE
        try:
            sys.stdout.close()
        except Exception:
            pass
        rc = 141  # 128 + SIGPIPE, the conventional shell status
    sys.exit(rc)


if __name__ == "__main__":
    main()
