"""The fleet router daemon (``pwasm-tpu route``).

One router process in front of N serve daemons (same host over unix
sockets, across hosts over TCP) exposes the FULL serve protocol —
submit/stream/result/cancel/status/inspect/stats/metrics/drain/ping —
on one endpoint, so "millions of users" stop dying at one socket on
one host:

- **placement**: every submit lands on the member with the least
  (queue depth + running + router-placed-but-not-yet-visible) load,
  refreshed from each member's registry-backed svc-stats by the health
  loop; a member answering ``queue_full`` is skipped for the next-best
  sibling before the client ever sees a 429;
- **global fair share**: client identities (explicit ``client=``,
  ``tok:`` tokens on TCP, peer uid on unix) get ONE fleet-wide
  admission quota in the :class:`~pwasm_tpu.fleet.ledger.FleetLedger`
  and ride every forwarded frame, so each member's DRR keeps being
  fair per member while no client can dodge its quota by spraying
  members;
- **journal-aware failover**: a member that dies mid-job (SIGKILL,
  OOM-kill, host loss) is detected by the health loop; the router
  reads the member's job journal (shared ``--journal-dir`` or the
  same-host ``<socket>.journal`` default — docs/FLEET.md placement
  policy) and re-admits every started-unfinished job to a sibling as
  a ``--resume`` continuation of its own report checkpoint — the PR 9
  kill -9 drill, across processes.  Jobs the journal shows FINISHED
  are served from their CRC-verified spool files; acked cancels stay
  cancelled; live streams land terminal preempted-RESUMABLE exactly
  as a restarting member would land them.  The consumed journal is
  then set aside (``<journal>.recovered``) so a later restart of that
  member cannot re-run work a sibling already owns.

The router holds no device, no queue of its own (members queue), and
no jax (``qa/check_supervision.py::find_fleet_violations``): it moves
frames, reads journals, and keeps the ledger.  Job identity: the
router mints fleet-wide ids (``fleet-NNNN``) and rewrites member ids
at the edge; the client-supplied ``trace_id`` is forwarded verbatim on
every frame — including failover re-admissions — so one
``trace-merge`` of client + router + member traces reconstructs a
job's whole cross-process, cross-crash life.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time

from pwasm_tpu.core.errors import EXIT_USAGE, PwasmError
from pwasm_tpu.fleet.fencing import (DEFAULT_LEASE_TTL_S,
                                     readmit_epoch_guard)
from pwasm_tpu.fleet.ledger import FleetLedger
from pwasm_tpu.fleet.transport import (connect, is_tcp_target,
                                       make_tcp_listener,
                                       member_journal_path,
                                       router_journal_path,
                                       target_name)
from pwasm_tpu.resilience.lifecycle import SignalDrain
from pwasm_tpu.service import protocol
from pwasm_tpu.service.client import ServiceClient, ServiceError
from pwasm_tpu.service.journal import (JOURNAL_VERSION, JobJournal,
                                       REC_EPOCH, REC_MEMBERS,
                                       REC_ROUTE_ADMIT,
                                       REC_ROUTE_PLACE,
                                       REC_ROUTE_RETIRE, REC_SCALE,
                                       REC_ROUTE_SHED, fold_records)
from pwasm_tpu.service.queue import (JOB_CANCELLED, JOB_DONE,
                                     JOB_FAILED, JOB_PREEMPTED,
                                     QueueFull, TERMINAL_STATES,
                                     _sum_numeric)

_ROUTE_USAGE = """Usage:
 pwasm-tpu route --backends=TARGET[,TARGET...]
                 (--socket=PATH | --listen=HOST:PORT) [both allowed]
                 [--journal-dir=DIR] [--max-queue=N]
                 [--max-queue-total=N] [--poll-interval=S]
                 [--lease-ttl=S] [--scale-policy=FILE]
                 [--stream-replay-bytes=N]
                 [--metrics-textfile=PATH] [--log-json=FILE]
                 [--trace-json=FILE] [--slo-rules=FILE|off]
                 [--result-cache=DIR|off]
                 [--result-cache-max-bytes=N]
                 [--tls-cert=PEM --tls-key=PEM [--tls-client-ca=PEM]]
                 [--member-tls-ca=PEM [--member-tls-cert=PEM
                  --member-tls-key=PEM]] [--member-token=TOKEN]
                 [--auth-tokens=FILE] [--rate-limit=N[/s][:burst]]
                 [--max-frame-bytes=N]
 pwasm-tpu route --standby-of=TARGET [--journal-dir=DIR]
                 [--poll-interval=S] [...primary flags inherited
                 on takeover, EXCEPT --backends/--socket/--listen]

   --backends=...       member serve daemons, comma-separated targets
                        (unix socket paths and/or HOST:PORT — required)
   --socket=PATH        unix socket to serve the fleet protocol on
   --listen=HOST:PORT   TCP endpoint to serve it on (port 0 = any)
   --journal-dir=DIR    where members journal (shared durable storage:
                        start each member with the same --journal-dir
                        so the router can read a dead member's journal
                        and fail its jobs over; without it only
                        same-host unix members — default
                        <socket>.journal — are recoverable)
   --max-queue=N        FLEET-WIDE per-client live-job quota
                        (default 64); past it a client's submit
                        answers queue_full on the router, no matter
                        which member it would have landed on
   --max-queue-total=N  fleet-wide live-job backstop (default 8x)
   --max-results=N      retired routed-job entries kept for id lookup
                        (default 4096, LRU by last access; results
                        themselves live on the members — an evicted
                        fleet id answers unknown_job)
   --poll-interval=S    member health/stats refresh period
                        (default 0.5; a live member is declared dead
                        only after 2 consecutive failed polls, or
                        instantly on a mid-request connection
                        failure)
   --standby-of=TARGET  run as the WARM STANDBY of the router serving
                        on unix socket TARGET: tail its write-ahead
                        journal, and when the primary stops answering
                        ping, take over its socket with the routed-job
                        table replayed (docs/FLEET.md).  Mutually
                        exclusive with --backends/--socket/--listen —
                        the standby inherits all three from the
                        primary's journal, never from flags
   --lease-ttl=S        epoch-lease TTL granted to members (default
                        15; heartbeated on every stats poll — keep it
                        well above 2x --poll-interval).  A member that
                        misses heartbeats for S seconds self-fences:
                        drains in-flight work to checkpoints and
                        refuses new frames until a fresh lease
   --scale-policy=FILE  SLO-driven member auto-scaling policy (JSON:
                        min/max members, spawn argv, cooldown,
                        hysteresis — docs/FLEET.md).  Queue-pressure/
                        burn-rate verdicts spawn `serve` members;
                        sustained calm drains the scaler's own
                        members back down
   --priority-lanes=A,B brownout tier order, highest first (mirror
                        the members' --priority-lanes): past the
                        queue-pressure SLO threshold the router sheds
                        admissions LOWEST tier first with a truthful
                        `overloaded` + retry_after_s — before any
                        member sees queue_full.  The top tier is
                        never shed (brownout, not blackout); without
                        this flag shedding is inert
   --quarantine-x=K     slow-member quarantine: a member whose
                        stats-poll latency EWMA sustains past K x the
                        fleet median (default 4, min 1, 0 = off) is
                        quarantined — no new placements, running jobs
                        finish, streams keep their member
   --quarantine-probation=N  consecutive clean polls before a
                        quarantined member takes placements again
                        (default 3)
   --stream-replay-bytes=N  per-stream replay window (default 4194304
                        = 4 MiB, 0 = off): un-acked stream records
                        buffered at the router so a member death
                        MID-STREAM re-drives them to a sibling
                        invisibly instead of answering re-open errors
   --result-cache=DIR   the members' SHARED result-cache dir
                        (docs/SERVICE.md; point members'
                        serve --result-cache at the same shared
                        storage, like --journal-dir): a submit whose
                        content key hits there is answered AT THE
                        ROUTER — no member, no queue, no device,
                        anywhere in the fleet.  On a miss the key
                        drives cache-AFFINITY placement: a member
                        whose `cache-probe` answers hit gets the job
   --result-cache-max-bytes=N  LRU-evict the router's cache dir past
                        N total bytes
   --metrics-textfile=PATH  node-exporter textfile of the fleet
                        families (pwasm_fleet_*, docs/OBSERVABILITY.md)
   --log-json=FILE      append NDJSON fleet events (member_down,
                        failover verdicts, placements)
   --trace-json=FILE    Chrome trace of the router's per-job spans
                        (route_submit / route_result_wait, stamped
                        with each job's trace_id) — `pwasm-tpu
                        trace-merge` joins it with the client's and
                        members' traces on one timeline
   --slo-rules=FILE|off JSON rules merged over the fleet default set
                        (member_down / failover_burst /
                        ledger_saturation — obs/catalog.py); the
                        router's `health` verb folds every member's
                        own verdict into ONE fleet verdict on top
                        ("off" disables the router's engine).
                        docs/OBSERVABILITY.md
   --tls-cert=PEM --tls-key=PEM  serve the router's TCP --listen
                        endpoint over TLS (1.2+; the unix socket stays
                        plaintext — filesystem permissions are its
                        auth).  Clients dial with --tls-ca
   --tls-client-ca=PEM  require mTLS client certificates signed by
                        this CA; the verified peer CN becomes the
                        connection's attested identity (`cn:<name>`),
                        ranking above client_token (docs/FLEET.md
                        Security model)
   --member-tls-ca=PEM  dial MEMBERS over TLS, verifying their server
                        certs against this CA (add --member-tls-cert/
                        --member-tls-key when members demand mTLS).
                        One config serves a mixed fleet: unix-socket
                        members ignore it
   --member-token=TOKEN client_token presented on every router→member
                        frame — required when members run
                        --auth-tokens (the stats poll carries the
                        lease grant, an admin-scope operation)
   --auth-tokens=FILE   scoped capability tokens (JSON, CRC-stamped,
                        hot-reloaded — docs/FLEET.md Security model).
                        Control verbs (drain/lease-grant/fence) demand
                        admin scope; unauthorized frames answer
                        `unauthorized` and touch no ledger state
   --rate-limit=N[/s][:burst]  per-client token-bucket in front of
                        fleet admission (edge rate limiting: a
                        refused submit reaches no member and writes
                        no journal) — refusals answer `overloaded`
                        with a truthful retry_after_s
   --max-frame-bytes=N  per-frame byte ceiling on the router edge
                        (default 8388608 = 8 MiB, mirroring the
                        members'); an oversized frame answers
                        frame_too_large on BOTH transports

 SIGTERM (or the `drain` command) latches admission shut; in-flight
 member jobs keep running and their results stay fetchable until the
 last routed job lands terminal, then the router exits 0.
"""


# consecutive health-poll failures before a live member is declared
# dead (the poll path is a timeout-prone 3s stats RPC; mid-request
# connection failures on the forwarding paths still count as instant
# evidence).  2 keeps real-death detection within ~2 poll ticks while
# absorbing a single slow poll.
_POLL_STRIKES = 2

# gray-failure defense (ISSUE 18) tuning that is policy, not knob:
# consecutive outlier polls before quarantine (2 = detection within
# ~2-3 poll ticks, one slow poll absorbed), the absolute latency
# floor below which nobody is an outlier (a local-socket fleet whose
# polls all land under 50 ms has no gray failures worth reacting to),
# and the pressure-free SLO evaluations required before the brownout
# shed controller de-escalates one priority tier (hysteresis — shed
# state must not flap with each queue-depth sample).
_Q_STRIKES = 2
_Q_FLOOR_MS = 50.0
_SHED_CLEAN_EXITS = 3


class _Member:
    """One backend serve daemon as the router sees it."""

    def __init__(self, target: str, journal_dir: str | None):
        self.target = target
        self.name = target_name(target)
        self.journal_path = member_journal_path(target, journal_dir)
        self.alive = False          # until the first healthy poll
        self.ever_alive = False
        self.queue_depth = 0
        self.running = 0
        self.stats: dict | None = None
        self.jobs_routed = 0
        self.fail_streak = 0
        self.cache_enabled: bool | None = None   # last cache-probe's
        #   enabled verdict: False skips this member in future
        #   affinity probes (one RPC saved per submit per member)
        self.dispatched_since_poll = 0   # router placements the
        #   member's last stats reply cannot have observed yet — the
        #   placement pressure term (reset on every successful poll,
        #   so a long-running routed job is never double-counted
        #   against the depth the member itself reports)
        self.fenced = False         # member reports itself fenced
        #   (lost epoch lease): reachable, but refusing new work
        self.scaled = False         # spawned by the SLO scaler (the
        #   only members the scaler may also retire)
        self.proc = None            # the scaler's child handle
        # ---- gray-failure detection (ISSUE 18): a member that is
        # ALIVE but pathologically slow (half-dead disk, GC storms,
        # a lossy NIC) passes every liveness poll while dragging the
        # fleet p99 down.  The router EWMAs each member's stats-poll
        # round-trip and its reported queue pressure; a sustained
        # latency outlier vs the fleet MEDIAN is quarantined — no new
        # placements, existing jobs finish, streams keep their member
        # — and probation-exits after clean polls.
        self.lat_ewma_ms = 0.0      # stats-RPC round-trip EWMA
        self.depth_ewma = 0.0       # queued+running EWMA (queue-wait
        #                             proxy, shown in svc-stats/top)
        self.quarantined = False
        self.q_strikes = 0          # consecutive outlier polls
        self.q_clean = 0            # consecutive clean polls while
        #                             quarantined (probation counter)
        self.quarantines = 0        # times this member entered


class _FleetJob:
    """One routed job: fleet id, current placement, and — after a
    failover recovered its verdict from journal+spool — the cached
    terminal result the router serves itself."""

    __slots__ = ("fid", "client", "priority", "trace_id", "frame",
                 "member", "mjid", "gen", "stream", "sconn", "slock",
                 "terminal", "retired", "failovers", "submitted_s",
                 "accessed_s", "recovering", "epoch", "rbuf",
                 "rbytes", "ended", "deadline_ms", "submitted_mono",
                 "scatter")

    def __init__(self, fid: str, client: str, priority: str,
                 trace_id: str, frame: dict, member: str, mjid: str,
                 stream: bool = False):
        self.fid = fid
        self.client = client
        self.priority = priority
        self.trace_id = trace_id
        self.frame = frame          # the ORIGINAL submit fields (args/
        #   cwd/...) — what a failover re-admission replays
        self.member = member
        self.mjid = mjid
        self.gen = 0                # placement generation (bumped per
        #   failover so result-waiters re-aim their member connection)
        self.stream = stream
        self.sconn = None           # persistent member conn for
        #   stream-data frames (one per stream job)
        self.slock = threading.Lock()
        self.terminal: dict | None = None   # router-served verdict
        self.retired = False        # ledger slot released
        self.failovers = 0
        self.submitted_s = time.time()
        self.accessed_s = time.time()   # LRU clock for table eviction
        self.deadline_ms = None     # REMAINING end-to-end budget at
        #   router admission (ISSUE 18); submitted_mono anchors the
        #   decrement so a failover re-placement forwards only what
        #   is genuinely left of the client's budget
        self.submitted_mono = time.monotonic()
        self.recovering = False     # orphan-recovery once-latch
        self.epoch = 0              # fleet epoch the CURRENT placement
        #   was made under (fencing: a re-placement must carry an
        #   epoch >= every prior placement's — readmit_epoch_guard)
        self.rbuf: list | None = [] if stream else None   # the
        #   bounded mid-stream replay window: acked stream-data/end
        #   frames a failover re-drives to a sibling (None = overflow
        #   or --stream-replay-bytes=0 — replay degrades to the
        #   terminal preempted-resumable verdict)
        self.rbytes = 0
        self.ended = False          # stream-end already acked
        self.scatter = None         # fleet-wide m2m surveillance
        #   (ISSUE 20): when this stream job is a scattered
        #   --m2m-stream, the router-side partition/merge state
        #   (surveil/partition.py) — per-member sub-streams, record
        #   assignment, replay buffers, fragment paths


def fold_route_records(records: list[dict]) -> dict:
    """Fold a replayed router-WAL stream (``REC_ROUTE_*`` / epoch /
    members / scale records — service/journal.py vocabulary) into the
    state a restarted router or a promoting standby rebuilds:

    - ``jobs``: one ``{"admit", "place", "retire", "_ord"}`` row per
      fleet job id, last-write-wins per kind, admit order preserved
      (rows with no admit are dropped — a torn admit line means the
      client was never acked);
    - ``epoch``: the highest journaled fleet epoch;
    - ``members``: the LAST members snapshot's backend target list
      (None if no snapshot survived — the standby then has no
      backends to adopt and must refuse the takeover);
    - ``scaled``: scaler-owned members still alive at the crash
      (spawn records minus retire records), by target."""
    jobs: dict[str, dict] = {}
    epoch = 0
    members: list | None = None
    scaled: dict[str, dict] = {}
    for rec in records:
        kind = rec.get("rec")
        if kind == REC_EPOCH:
            e = rec.get("epoch")
            if isinstance(e, int) and e > epoch:
                epoch = e
            continue
        if kind == REC_MEMBERS:
            b = rec.get("backends")
            if isinstance(b, list) \
                    and all(isinstance(t, str) for t in b):
                members = b
            continue
        if kind == REC_SCALE:
            t = rec.get("target")
            if isinstance(t, str):
                if rec.get("action") == "spawn":
                    scaled[t] = rec
                else:
                    scaled.pop(t, None)
            continue
        fid = rec.get("job_id")
        if not isinstance(fid, str):
            continue
        if kind == REC_ROUTE_ADMIT:
            jobs.setdefault(fid, {"admit": rec, "place": None,
                                  "retire": None, "_ord": len(jobs)})
            continue
        row = jobs.get(fid)
        if row is None:
            continue
        if kind == REC_ROUTE_PLACE:
            row["place"] = rec
        elif kind == REC_ROUTE_RETIRE:
            row["retire"] = rec
    return {"jobs": jobs, "epoch": epoch, "members": members,
            "scaled": scaled}


class Router:
    """The fleet router.  ``serve()`` runs the accept + health loops;
    everything else is the per-connection protocol dispatch."""

    def __init__(self, backends: list[str],
                 socket_path: str | None = None,
                 listen: str | None = None,
                 journal_dir: str | None = None,
                 max_queue: int = 64,
                 max_queue_total: int | None = None,
                 poll_interval: float = 0.5,
                 max_results: int = 4096,
                 stderr=None, metrics_textfile: str | None = None,
                 log_json: str | None = None,
                 trace_json: str | None = None,
                 slo_rules=None,
                 result_cache: str | None = None,
                 result_cache_max_bytes: int | None = None,
                 lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
                 scale_policy: dict | None = None,
                 stream_replay_bytes: int = 4 << 20,
                 takeover: bool = False,
                 priority_lanes: tuple | list | None = None,
                 quarantine_x: float = 4.0,
                 quarantine_probation: int = 3,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 tls=None, member_tls=None,
                 member_token: str | None = None,
                 auth_tokens: str | None = None,
                 rate_limit: tuple | None = None):
        if not backends:
            raise ValueError("route needs at least one backend")
        if not socket_path and not listen:
            raise ValueError("route needs --socket and/or --listen")
        self.socket_path = socket_path
        self.listen = listen
        self.journal_dir = journal_dir
        self.lease_ttl_s = max(0.1, float(lease_ttl_s))
        self.stream_replay_bytes = max(0, int(stream_replay_bytes))
        self.takeover = bool(takeover)
        self.epoch = 0               # fleet epoch (fencing token);
        #   _open_journal replays the highest journaled epoch and
        #   bumps it — every router incarnation is a new era
        self.tcp_port: int | None = None    # actual port after bind
        self.stderr = stderr if stderr is not None else sys.stderr
        self.poll_interval = max(0.05, float(poll_interval))
        self.members: dict[str, _Member] = {}
        for t in backends:
            m = _Member(t, journal_dir)
            if m.name in self.members:
                raise ValueError(
                    f"two backends map to member name {m.name!r} "
                    f"({self.members[m.name].target!r} and {t!r}) — "
                    "give them distinct basenames/ports")
            self.members[m.name] = m
        self.ledger = FleetLedger(max_queue, max_queue_total)
        self.max_results = max(1, int(max_results))
        self.jobs: dict[str, _FleetJob] = {}
        self._clients_seen: set[str] = set()   # label universe for
        #   the per-client gauge (a retired client reads 0, not gone)
        self.drain = SignalDrain(stderr=self.stderr)
        self._lock = threading.Lock()
        self._draining = False
        self._closing = threading.Event()
        self._next_id = 0
        self._rr = 0                 # placement tie-breaker
        self._t0 = time.monotonic()  # uptime anchor — monotonic, a
        #   wall-clock step must not warp uptime_s (qa clock gate)
        # ---- gray-failure defense (ISSUE 18): slow-member
        # quarantine tuning + the brownout shed controller.
        # priority_lanes mirrors the members' --priority-lanes tier
        # order (highest first); shedding turns the LOWEST tier away
        # first and the top tier is never shed — a brownout, not a
        # blackout.  quarantine_x = the outlier multiple over the
        # fleet-median poll round-trip EWMA (0 disables);
        # quarantine_probation = clean polls before a quarantined
        # member takes placements again.
        self.priority_lanes = tuple(priority_lanes or ())
        self.quarantine_x = float(quarantine_x)
        self.quarantine_probation = max(1, int(quarantine_probation))
        self._shed_level = 0         # how many tiers (from the
        #   bottom) are currently turned away
        self._shed_clean = 0         # consecutive pressure-free SLO
        #   evaluations (hysteresis: de-escalate one tier per
        #   _SHED_CLEAN_EXITS clean evals, never flap per-tick)
        self._shed_last = 0.0        # the controller's own cadence
        #   anchor — slo._last_eval is reset by stats-verb
        #   evaluations too, so it cannot pace the shed loop
        self.failovers = 0           # member-death events handled
        self.recovered = {"resumed": 0, "requeued": 0, "restored": 0,
                          "cancelled": 0, "stream_preempted": 0,
                          "stream_replayed": 0, "failed": 0,
                          "deadline_exceeded": 0}
        # ---- router write-ahead journal (ISSUE 16): every routed
        # admission/placement/retirement + epoch bumps + member-set
        # snapshots, fsync'd per batch through the same JobJournal the
        # members use.  None = journal-less (TCP-only endpoint with
        # no --journal-dir): today's RAM-only behaviour, said loudly.
        jpath = router_journal_path(socket_path, listen, journal_dir)
        self.rjournal = JobJournal(jpath) if jpath else None
        self._rjournal_warned = False
        from pwasm_tpu.obs import (EventLog, MetricsRegistry,
                                   Observability, TraceRecorder)
        from pwasm_tpu.obs.catalog import build_fleet_metrics
        self.registry = MetricsRegistry()
        self.metrics = build_fleet_metrics(self.registry)
        self.metrics["members"].set(len(self.members))
        self.metrics_textfile = metrics_textfile
        events = EventLog(path=log_json) if log_json else None
        tracer = TraceRecorder() if trace_json else None
        self.obs = Observability(registry=self.registry,
                                 events=events, tracer=tracer,
                                 trace_path=trace_json)
        self.drain.obs = self.obs
        self.log_json_path = log_json   # the `logs` verb reads it
        # ---- fleet self-monitoring (ISSUE 14): the router's own SLO
        # engine over the pwasm_fleet_* families (member_down,
        # failover_burst, ledger_saturation by default; user rules
        # merge by name), plus the member-verdict aggregation the
        # `health` verb performs on demand
        from pwasm_tpu.obs.catalog import (build_slo_metrics,
                                           default_fleet_slo_rules)
        from pwasm_tpu.obs.slo import SloEngine, merge_rules
        self.metrics["max_jobs"].set(self.ledger.max_total)
        self.slo_metrics = build_slo_metrics(self.registry)
        if slo_rules == "off":
            rules = []
        else:
            rules = merge_rules(default_fleet_slo_rules(), slo_rules)
        self.slo = SloEngine(self.registry, rules,
                             metrics=self.slo_metrics,
                             on_event=self.obs.event,
                             eval_interval_s=min(
                                 1.0, self.poll_interval))
        # ---- fleet result cache (ISSUE 15): `route --result-cache`
        # points at the MEMBERS' shared cache dir (the --journal-dir
        # placement idea — shared durable storage).  A submit whose
        # key hits there is answered AT THE ROUTER: no member, no
        # queue, no device, anywhere.  On a miss the computed key is
        # also used for cache-AFFINITY placement: a member answering
        # the `cache-probe` verb hit=true gets the job (its own
        # admission then serves it from its private cache), so a job
        # already answered by ANY member never re-runs.
        from pwasm_tpu.obs.catalog import build_cache_metrics
        self.cache_metrics = build_cache_metrics(self.registry)
        self.cache = None
        if result_cache and result_cache != "off":
            from pwasm_tpu.service.cache import CacheStore
            try:
                self.cache = CacheStore(
                    result_cache, max_bytes=result_cache_max_bytes,
                    metrics=self.cache_metrics)
            except OSError as e:
                self._say(f"warning: --result-cache dir "
                          f"{result_cache} unusable ({e}); fleet "
                          "result caching disabled")
        # ---- SLO-driven member auto-scaling (ISSUE 16): the scaler
        # turns the engine's queue-pressure/burn-rate verdicts into
        # spawn/retire actions inside [min,max] bounds, journaled so
        # a restarted router re-adopts the members it owns
        self.scaler = None
        if scale_policy:
            from pwasm_tpu.fleet.scaler import FleetScaler
            self.scaler = FleetScaler(self, scale_policy)
        # ---- zero-trust edge (ISSUE 19), mirroring the serve daemon:
        # TLS on the router's own TCP listener, ClientTLS + capability
        # token for every router->member dial (the _dial factory), a
        # scoped-token gate on the edge, and the edge rate limiter.
        # All opt-in; unarmed the router is byte-identical to PR 18.
        self.max_frame_bytes = int(max_frame_bytes)
        self.tls = tls                     # transport.ServerTLS | None
        self.member_tls = member_tls       # transport.ClientTLS | None
        self.member_token = member_token
        from pwasm_tpu.obs.catalog import build_transport_metrics
        self.transport_metrics = build_transport_metrics(self.registry)
        self.auth = None
        self._penalty = None
        if auth_tokens:
            from pwasm_tpu.service.authz import (AuthRegistry,
                                                 PenaltyBox)
            self.auth = AuthRegistry(auth_tokens, say=self._say)
            self._penalty = PenaltyBox()
        self._auth_labels: set = set()
        self.rate_limiter = None
        if rate_limit is not None:
            from pwasm_tpu.service.queue import RateLimiter
            self.rate_limiter = RateLimiter(rate_limit[0],
                                            rate_limit[1])

    # ---- lifecycle -----------------------------------------------------
    def serve(self) -> int:
        import selectors
        listeners: list[socket.socket] = []
        try:
            if self.socket_path:
                from pwasm_tpu.fleet.transport import (
                    make_unix_listener, socket_alive)
                if os.path.exists(self.socket_path) \
                        and socket_alive(self.socket_path):
                    raise PwasmError(
                        f"Error: something is already serving on "
                        f"{self.socket_path}\n")
                # the factory chmods the socket 0600 (ISSUE 19):
                # local clients are the serving uid; TCP is the
                # opt-in wider audience, with TLS/auth as its gate
                listeners.append(make_unix_listener(self.socket_path))
            if self.listen:
                t = make_tcp_listener(self.listen)
                self.tcp_port = t.getsockname()[1]
                listeners.append(t)
        except OSError as e:
            for s in listeners:
                s.close()
            raise PwasmError(
                f"Error: cannot bind router endpoint: {e}\n")
        sel = selectors.DefaultSelector()
        for s in listeners:
            s.setblocking(False)
            sel.register(s, selectors.EVENT_READ)
        self._open_journal()         # replay + epoch bump BEFORE the
        #   first poll — the first heartbeat must carry the new era
        if self.takeover:
            self.metrics["takeovers"].inc()
            self.obs.event("standby_takeover", epoch=self.epoch,
                           socket=self.socket_path)
        self._poll_members()         # first placement view up front
        health = threading.Thread(target=self._health_loop,
                                  daemon=True,
                                  name="pwasm-route-health")
        with self.drain:
            health.start()
            where = " + ".join(
                ([self.socket_path] if self.socket_path else [])
                + ([f"{self.listen.rsplit(':', 1)[0]}:"
                    f"{self.tcp_port}"] if self.listen else []))
            self._say(f"routing {len(self.members)} member(s) on "
                      f"{where}")
            self.obs.event("router_start", members=len(self.members),
                           backends=[m.target for m in
                                     self.members.values()])
            self._write_textfile()
            drained_at = None
            try:
                while True:
                    if self.auth is not None:
                        # token rotation without a restart (same
                        # keep-last-good reload as the members)
                        self.auth.maybe_reload()
                    if self.drain.requested:
                        self._begin_drain(self.drain.reason
                                          or "drain requested")
                        if self._drained():
                            if drained_at is None:
                                drained_at = time.monotonic()
                            elif time.monotonic() - drained_at > 0.5:
                                break
                    try:
                        events = sel.select(0.2)
                    except OSError:
                        break
                    for key, _ in events:
                        try:
                            conn, _addr = key.fileobj.accept()
                        except OSError:
                            continue
                        conn.setblocking(True)
                        threading.Thread(target=self._handle_conn,
                                         args=(conn,),
                                         daemon=True).start()
            finally:
                self._closing.set()
                sel.close()
                for s in listeners:
                    s.close()
                with self._lock:
                    sconns = [j.sconn for j in self.jobs.values()
                              if j.sconn is not None]
                for sc in sconns:
                    sc.close()
                if self.socket_path:
                    try:
                        os.unlink(self.socket_path)
                    except OSError:
                        pass
        if self.scaler is not None:
            self.scaler.shutdown()
        if self.rjournal is not None:
            if self.drain.requested and self._drained():
                # clean drain: every routed job landed terminal and
                # every client could read it — nothing to recover
                self.rjournal.unlink()
            else:
                self.rjournal.close()
        self.obs.event("router_exit", drained=self.drain.requested)
        self._write_textfile()
        if self.obs.tracer is not None and self.obs.trace_path:
            try:
                self.obs.tracer.write(self.obs.trace_path)
                self._say(f"trace written to {self.obs.trace_path}")
            except OSError as e:
                self._say(f"warning: cannot write --trace-json "
                          f"{self.obs.trace_path}: {e}")
        if self.obs.events is not None:
            self.obs.events.close()
        if self.drain.requested:
            self._say("drained — every routed job landed terminal; "
                      "members keep serving")
        return 0

    def _say(self, msg: str) -> None:
        print(f"pwasm-route: {msg}", file=self.stderr)

    def _drained(self) -> bool:
        with self._lock:
            return self._draining and all(
                j.retired or j.terminal is not None
                for j in self.jobs.values())

    def _begin_drain(self, reason: str) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            live = sum(1 for j in self.jobs.values()
                       if not j.retired and j.terminal is None)
        self.obs.event("router_drain", reason=reason, live=live)
        self._say(f"draining ({reason}): {live} routed job(s) still "
                  "live on members; results stay fetchable, new "
                  "submissions rejected")

    # ---- write-ahead journal (ISSUE 16) --------------------------------
    def _journal(self, rows: list) -> None:
        """Durably append ``[(rec, fields), ...]`` in one fsync;
        degrades loudly (warn once, keep routing) like the member
        journal — a full disk costs the HA guarantee, not the fleet."""
        if self.rjournal is None:
            return
        now = round(time.time(), 3)
        stamped = [(rec, dict(fields, t=now)) for rec, fields in rows]
        if self.rjournal.append_many(stamped):
            for rec, _f in rows:
                self.metrics["journal_records"].inc(rec=rec)
        elif self.rjournal.broken and not self._rjournal_warned:
            self._rjournal_warned = True
            self._say("warning: router journal append failed "
                      f"({self.rjournal.broken}); continuing WITHOUT "
                      "crash-safe routing — a router crash now loses "
                      "the routed-job table")

    def _open_journal(self) -> None:
        """Open (and replay) the router WAL.  Replay rebuilds the
        routed-job table — live placements re-enter the ledger without
        re-running the quota gate (their admissions were acked),
        journaled terminal verdicts are served from the router again —
        then the epoch is bumped: every incarnation is a new era, so
        members leased to the dead incarnation re-lease or fence."""
        if self.rjournal is None:
            self._say("warning: no durable journal path for this "
                      "endpoint (TCP-only, no --journal-dir): routing "
                      "is NOT crash-safe and no standby can follow")
            return
        records = self.rjournal.replay()
        self.rjournal.open()
        folded = fold_route_records(records) if records else None
        replayed = 0
        if folded is not None:
            replayed = self._replay_state(folded)
            self.epoch = max(self.epoch, folded["epoch"])
        self.epoch += 1
        self._compact_journal()
        self.metrics["epoch"].set(self.epoch)
        if replayed:
            self.metrics["journal_replayed"].inc(replayed)
            self.obs.event("router_journal_replay", jobs=replayed,
                           epoch=self.epoch)
            self._say(f"replayed {replayed} routed job(s) from "
                      f"{self.rjournal.path} (fleet epoch now "
                      f"{self.epoch})")

    def _replay_state(self, folded: dict) -> int:
        """Rebuild the routed-job table from a fold; returns how many
        jobs were restored (live + terminal)."""
        backends = folded.get("members")
        if backends:
            for t in backends:
                self._add_member(t)
        for t in folded.get("scaled", {}):
            self._add_member(t, scaled=True)
        restored = 0
        rows = sorted(folded["jobs"].items(),
                      key=lambda kv: kv[1]["_ord"])
        for fid, row in rows:
            try:
                n = int(fid.rsplit("-", 1)[-1])
            except ValueError:
                n = 0
            self._next_id = max(self._next_id, n)
            admit = row["admit"]
            place = row["place"]
            retire = row["retire"]
            frame = admit.get("frame")
            if not isinstance(frame, dict):
                continue
            stream = bool(admit.get("stream"))
            job = _FleetJob(fid, str(admit.get("client") or ""),
                            str(admit.get("priority") or ""),
                            str(admit.get("trace_id") or ""),
                            frame,
                            str((place or {}).get("member")
                                or "cache"),
                            str((place or {}).get("mjid") or ""),
                            stream=stream)
            if place is not None:
                job.gen = int(place.get("gen") or 0)
                job.epoch = int(place.get("epoch") or 0)
            if stream:
                # the replay window died with the old process and the
                # stream socket died with the client's connection —
                # a live stream cannot survive a ROUTER death, only a
                # member death.  Land it the way a member restart
                # would: terminal preempted-resumable.
                job.rbuf = None
            sub = admit.get("t")
            if isinstance(sub, (int, float)):
                job.submitted_s = float(sub)
            self.jobs[fid] = job
            restored += 1
            if retire is not None:
                job.retired = True
                state = retire.get("state")
                if state in TERMINAL_STATES:
                    rc = retire.get("rc") \
                        if isinstance(retire.get("rc"), int) else None
                    job.terminal = protocol.ok(
                        job={"id": fid, "state": state, "rc": rc,
                             "detail": str(retire.get("detail")
                                           or "")
                             + " [replayed from the router journal]",
                             "client": job.client,
                             "priority": job.priority,
                             "trace_id": job.trace_id,
                             "stream": stream, "recovered": True,
                             "member": job.member,
                             "submitted_s": round(job.submitted_s, 3),
                             "started_s": None,
                             "finished_s": retire.get("t")},
                        rc=rc, stats=None, stderr_tail="")
                continue
            if stream:
                job.recovering = True   # hold the health loop off
                self._cache_terminal(job, JOB_PREEMPTED, 75, (
                    "stream interrupted: the fleet router restarted "
                    "and the stream connection died with it; records "
                    "up to the last checkpoint are durable — re-open "
                    "a stream with --resume and re-send the records"))
                job.recovering = False
                self.recovered["stream_preempted"] += 1
                self.metrics["recovered"].inc(how="stream_preempted")
                continue
            if place is None or job.member == "cache":
                # admitted but never placed (crash in the gap): the
                # admission was never acked either — the ack and the
                # place record commit together — so drop it
                job.retired = True
                continue
            # live placement: re-enter the ledger WITHOUT the quota
            # gate (the admission promise predates this incarnation)
            self.ledger.restore(job.client, job.member)
        return restored

    def _compact_journal(self) -> None:
        """Atomically rewrite the WAL to current state: one members
        snapshot, the current epoch, the scaler's live spawns, then
        admit(+place)(+retire) per surviving job — restart cost stays
        bounded by the table, not router-lifetime traffic."""
        if self.rjournal is None:
            return
        now = round(time.time(), 3)

        def raw(rec: str, **fields) -> dict:
            obj = {"v": JOURNAL_VERSION, "rec": rec, "t": now}
            obj.update(fields)
            return obj

        with self._lock:
            backends = [m.target for m in self.members.values()
                        if not m.scaled]
            scaled = [(m.target, getattr(m.proc, "pid", None))
                      for m in self.members.values() if m.scaled]
            jobs = sorted(self.jobs.values(),
                          key=lambda j: j.submitted_s)
            rows = [raw(REC_MEMBERS, backends=backends),
                    raw(REC_EPOCH, epoch=self.epoch)]
            for target, pid in scaled:
                rows.append(raw(REC_SCALE, action="spawn",
                                target=target, pid=pid))
            for j in jobs:
                rows.append(raw(
                    REC_ROUTE_ADMIT, job_id=j.fid, client=j.client,
                    priority=j.priority, trace_id=j.trace_id,
                    stream=j.stream, frame=j.frame,
                    t=round(j.submitted_s, 3)))
                if j.member != "cache":
                    rows.append(raw(
                        REC_ROUTE_PLACE, job_id=j.fid,
                        member=j.member, mjid=j.mjid, gen=j.gen,
                        epoch=j.epoch))
                if j.retired or j.terminal is not None:
                    f = {}
                    if isinstance(j.terminal, dict) \
                            and isinstance(j.terminal.get("job"),
                                           dict):
                        tj = j.terminal["job"]
                        f = {"state": tj.get("state"),
                             "rc": tj.get("rc"),
                             "detail": tj.get("detail")}
                    rows.append(raw(REC_ROUTE_RETIRE, job_id=j.fid,
                                    **f))
        try:
            self.rjournal.compact(rows)
        except OSError as e:
            if not self._rjournal_warned:
                self._rjournal_warned = True
                self._say(f"warning: router journal compaction "
                          f"failed ({e}); continuing on the old file")

    # ---- member-set mutation (takeover adoption + scaler) --------------
    def _add_member(self, target: str, scaled: bool = False):
        """Idempotently add a backend (journal-replay adoption or a
        scaler spawn).  Returns the member."""
        with self._lock:
            name = target_name(target)
            m = self.members.get(name)
            if m is None:
                m = _Member(target, self.journal_dir)
                m.scaled = scaled
                self.members[name] = m
                n = len(self.members)
            else:
                n = None
        if n is not None:
            self.metrics["members"].set(n)
        return m

    def _remove_member(self, name: str) -> None:
        """Forget a member (scaler retire): MUST run before the drain
        RPC so its planned exit never reads as a death to fail over."""
        with self._lock:
            self.members.pop(name, None)
            n = len(self.members)
        self.metrics["members"].set(n)
        self.metrics["member_up"].set(0, member=name)

    # ---- member health + placement -------------------------------------
    def _health_loop(self) -> None:
        while not self._closing.wait(self.poll_interval):
            self._poll_members(count_failures=True)
            self._reap_finished()
            self._evict_jobs()
            if self.slo.due():
                self.slo.evaluate()   # gauges fresh from the poll
            self._shed_tick()   # every tick, self-paced: due() can
            #   stay false forever under a fast stats-poll loop (the
            #   stats verb evaluates directly), and the brownout must
            #   not be starved by the operator watching the fleet
            if self.scaler is not None:
                self.scaler.tick()
            if self.rjournal is not None \
                    and self.rjournal.records_written > max(
                        1024, 8 * (len(self.jobs) + 1)):
                # the WAL grew well past live state: fold it back down
                self._compact_journal()
            self._write_textfile()

    def _poll_members(self, count_failures: bool = False) -> None:
        """Refresh every member's liveness + load.  Only the health
        loop passes ``count_failures=True``: it is single-threaded, so
        ``fail_streak`` really counts CONSECUTIVE health ticks — a
        stats request's synchronous refresh racing the loop must not
        double-count one member stall into two strikes and fail over
        a live member (the double-run corruption failover exists to
        prevent)."""
        for m in list(self.members.values()):
            t_rpc = time.monotonic()
            try:
                with self._dial(m.target, timeout=3.0) as c:
                    # the epoch lease rides the stats poll: every
                    # healthy tick IS the heartbeat, so fencing needs
                    # no extra RPC round and no extra timer
                    st = c.request({
                        "cmd": "stats",
                        **({"lease": {"epoch": self.epoch,
                                      "ttl_s": self.lease_ttl_s}}
                           if self.epoch >= 1 else {})})
                if not st.get("ok"):
                    raise ServiceError(f"stats failed: {st}")
                lat_ms = (time.monotonic() - t_rpc) * 1000.0
                stats = st["stats"]
                lease = stats.get("lease")
                lease = lease if isinstance(lease, dict) else {}
                with self._lock:
                    revived = not m.alive and m.ever_alive
                    m.alive = True
                    m.ever_alive = True
                    m.fail_streak = 0
                    m.stats = stats
                    m.queue_depth = int(stats.get("queue_depth") or 0)
                    m.running = int(stats.get("running") or 0)
                    # this reply has observed everything we placed
                    # before the RPC — stop counting it as pressure
                    m.dispatched_since_poll = 0
                    m.fenced = bool(lease.get("fenced"))
                    # gray-failure EWMAs (ISSUE 18): round-trip
                    # latency + queue pressure, ~alpha 0.3 so one
                    # stall neither dominates nor hides.  Only
                    # SUCCESSFUL polls feed them — a refused connect
                    # is death evidence (fail_streak), not latency.
                    m.lat_ewma_ms = lat_ms if m.lat_ewma_ms <= 0.0 \
                        else 0.3 * lat_ms + 0.7 * m.lat_ewma_ms
                    m.depth_ewma = (0.3 * (m.queue_depth + m.running)
                                    + 0.7 * m.depth_ewma)
                if lease.get("accepted") is False:
                    # the member holds a NEWER epoch than ours: WE are
                    # the stale incarnation (a zombie primary racing
                    # its own standby's takeover) — say so loudly
                    self.obs.event(
                        "lease_refused", member=m.name,
                        member_epoch=lease.get("epoch"),
                        epoch=self.epoch,
                        detail=str(lease.get("refused_detail") or ""))
                if revived:
                    self.obs.event("member_up", member=m.name)
                    self._say(f"member {m.name} is back")
            except (ServiceError, OSError, ValueError, TypeError,
                    KeyError):
                if not count_failures:
                    continue
                down = False
                with self._lock:
                    m.fail_streak += 1
                    # a never-seen member just hasn't started yet.  A
                    # known-alive member is declared dead only after
                    # _POLL_STRIKES CONSECUTIVE poll failures: one
                    # missed 3s stats RPC can be a load spike or a
                    # long compile.  (A genuinely dead daemon refuses
                    # the connect instantly, so real death still
                    # resolves within ~2 poll ticks.)
                    if m.alive and m.fail_streak >= _POLL_STRIKES:
                        down = True
                if down:
                    self._member_down(m.name)
        if count_failures:
            # quarantine transitions only on the single-threaded
            # health tick — a synchronous stats refresh racing the
            # loop must not double-count one outlier poll into two
            # strikes (the fail_streak rule, same reason)
            self._quarantine_scan()
        self._refresh_gauges()

    def _quarantine_scan(self) -> None:
        """Slow-member quarantine (ISSUE 18): after each health tick,
        compare every live member's poll-latency EWMA against the
        fleet MEDIAN.  A member sustained past ``quarantine_x`` times
        the median (with an absolute floor so microsecond-fast local
        fleets don't quarantine noise) for ``_Q_STRIKES`` consecutive
        polls is quarantined: no NEW placements, running jobs finish,
        streams keep their member.  It probation-exits after
        ``quarantine_probation`` consecutive clean polls.  The fleet
        is never wedged: a member is only quarantined while at least
        2 eligible members remain, and placement falls back to
        quarantined members when nothing else is alive."""
        if self.quarantine_x <= 0:
            return
        entered: list[tuple[str, float, float]] = []
        exited: list[tuple[str, float, float]] = []
        with self._lock:
            sampled = [m for m in self.members.values()
                       if m.alive and m.lat_ewma_ms > 0.0]
            if len(sampled) < 2:
                return      # a median of one member is the member
            lats = sorted(m.lat_ewma_ms for m in sampled)
            median = lats[len(lats) // 2]
            cut = max(self.quarantine_x * median, _Q_FLOOR_MS)
            eligible = sum(1 for m in sampled
                           if not m.fenced and not m.quarantined)
            for m in sampled:
                if m.lat_ewma_ms > cut:
                    m.q_strikes += 1
                    m.q_clean = 0
                    if (not m.quarantined
                            and m.q_strikes >= _Q_STRIKES
                            and eligible >= 2):
                        m.quarantined = True
                        m.quarantines += 1
                        eligible -= 1
                        entered.append((m.name, m.lat_ewma_ms,
                                        median))
                else:
                    m.q_strikes = 0
                    if m.quarantined:
                        m.q_clean += 1
                        if m.q_clean >= self.quarantine_probation:
                            m.quarantined = False
                            m.q_clean = 0
                            exited.append((m.name, m.lat_ewma_ms,
                                           median))
        for name, lat, med in entered:
            self.metrics["quarantines"].inc()
            self.obs.event("member_quarantined", member=name,
                           lat_ewma_ms=round(lat, 2),
                           fleet_median_ms=round(med, 2))
            self._say(f"member {name} QUARANTINED: poll latency "
                      f"{lat:.0f} ms vs fleet median {med:.0f} ms — "
                      "no new placements until it recovers")
        for name, lat, med in exited:
            self.obs.event("member_recovered", member=name,
                           lat_ewma_ms=round(lat, 2),
                           fleet_median_ms=round(med, 2))
            self._say(f"member {name} left quarantine "
                      f"({self.quarantine_probation} clean polls)")

    def _refresh_gauges(self) -> None:
        with self._lock:
            rows = [(m.name, m.alive, m.queue_depth + m.running,
                     m.lat_ewma_ms, m.quarantined)
                    for m in self.members.values()]
            live = sum(1 for j in self.jobs.values()
                       if not j.retired and j.terminal is None)
            fenced = sum(1 for m in self.members.values()
                         if m.alive and m.fenced)
            scaled = sum(1 for m in self.members.values()
                         if m.alive and m.scaled)
            shed_level = self._shed_level
        for name, alive, depth, lat, quar in rows:
            self.metrics["member_up"].set(1 if alive else 0,
                                          member=name)
            self.metrics["member_queue_depth"].set(depth, member=name)
            self.metrics["member_latency_ewma"].set(round(lat, 2),
                                                    member=name)
            self.metrics["member_quarantined"].set(
                1 if (alive and quar) else 0, member=name)
        self.metrics["shedding"].set(shed_level)
        self.metrics["live_jobs"].set(live)
        self.metrics["epoch"].set(self.epoch)
        self.metrics["fenced_members"].set(fenced)
        self.metrics["scaler_members"].set(scaled)
        depths = self.ledger.client_depths()
        with self._lock:
            self._clients_seen |= set(depths)
            if len(self._clients_seen) > 1024:
                # identities are client-minted on TCP (tok:...): cap
                # the label universe or a token-cycling client grows
                # router memory and the textfile forever.  Retired
                # (zero-depth) series are dropped oldest-set-first;
                # live clients always keep theirs.
                for c in list(self._clients_seen):
                    if c not in depths:
                        self._clients_seen.discard(c)
                    if len(self._clients_seen) <= 1024:
                        break
            clients = set(self._clients_seen)
        for c in clients:
            # every client ever routed keeps a series (bounded
            # above): a fully retired client must read 0, not freeze
            # at its last nonzero sample (the daemon's gauge rule)
            self.metrics["client_jobs"].set(depths.get(c, 0),
                                            client=c or "default")

    def _write_textfile(self) -> None:
        if not self.metrics_textfile:
            return
        try:
            self.registry.write_textfile(self.metrics_textfile)
        except OSError as e:
            self._say(f"warning: cannot write --metrics-textfile "
                      f"{self.metrics_textfile}: {e}")

    def _reap_finished(self) -> None:
        """Release ledger slots of jobs that finished on their member
        even if no client ever fetched the result — a quota must track
        LIVE work, not politeness."""
        with self._lock:
            pending = [j for j in self.jobs.values() if not j.retired]
        by_member: dict[str, list[_FleetJob]] = {}
        for j in pending:
            if j.terminal is not None:
                self._note_retired(j)   # router-cached verdict
            elif j.scatter is None:
                # scattered jobs are excluded: j.mjid is only sub 0 —
                # a terminal sub 0 does NOT mean the fleet-wide job is
                # done (the merge in _scatter_result decides that)
                by_member.setdefault(j.member, []).append(j)
        for name, jobs in by_member.items():
            with self._lock:
                m = self.members.get(name)
                if m is None or not m.alive:
                    continue
            try:
                with self._dial(m.target, timeout=3.0) as c:
                    for j in jobs:
                        st = c.status(j.mjid)
                        if st.get("ok") and st["job"]["state"] \
                                in TERMINAL_STATES:
                            self._note_retired(j)
            except (ServiceError, OSError, KeyError, TypeError):
                continue

    def _note_retired(self, job: _FleetJob) -> None:
        with self._lock:
            if job.retired:
                return
            job.retired = True
            sconn, job.sconn = job.sconn, None
            term = job.terminal
        if sconn is not None:
            # a terminal stream job's persistent member connection
            # would otherwise leak one fd here and one blocked handler
            # thread on the member for the router's whole life
            sconn.close()
        if job.scatter is not None:
            for row in job.scatter["subs"]:
                row["live"] = False
                try:
                    row["conn"].close()
                except Exception:
                    pass
        self.ledger.retire(job.client, job.member)
        fields: dict = {"job_id": job.fid}
        if isinstance(term, dict) and isinstance(term.get("job"),
                                                 dict):
            tj = term["job"]
            fields.update(state=tj.get("state"), rc=tj.get("rc"),
                          detail=tj.get("detail"))
        self._journal([(REC_ROUTE_RETIRE, fields)])

    def _evict_jobs(self) -> None:
        """Bound the routed-job table: RETIRED jobs past
        ``max_results`` are dropped least-recently-accessed first
        (their results live on the members; an evicted fleet id
        answers unknown_job, same contract as daemon eviction).  Live
        jobs are never candidates — the ledger and failover need
        them."""
        with self._lock:
            retired = [j for j in self.jobs.values() if j.retired]
            excess = len(retired) - self.max_results
            if excess <= 0:
                return
            retired.sort(key=lambda j: j.accessed_s)
            for j in retired[:excess]:
                self.jobs.pop(j.fid, None)

    # ---- brownout shedding (ISSUE 18) ----------------------------------
    def _shed_tick(self) -> None:
        """Overload controller, run every health tick on its OWN
        cadence (``self.slo.eval_interval_s``), not gated on
        ``slo.due()``: the stats verb evaluates the engine directly,
        so a client polling stats faster than the eval interval would
        keep ``due()`` false forever and starve this controller — the
        operator watching the fleet would be the very thing stopping
        it from shedding.  While a queue-pressure rule
        (``fleet_queue_pressure`` or ``ledger_saturation``) is
        firing, escalate the shed level one priority tier per tick —
        lowest tier first, the top tier never — and de-escalate one
        tier only after ``_SHED_CLEAN_EXITS`` consecutive clean ticks
        (hysteresis).  Inert without ``--priority-lanes``: with one
        implicit tier there is nothing to brown out that plain
        queue_full doesn't already say."""
        max_level = max(0, len(self.priority_lanes) - 1)
        if max_level == 0:
            return
        now = time.monotonic()
        if now - self._shed_last < self.slo.eval_interval_s:
            return
        self._shed_last = now
        pressure = any(f.get("rule") in ("fleet_queue_pressure",
                                         "ledger_saturation")
                       for f in self.slo.firing())
        level = self._shed_level
        if pressure:
            self._shed_clean = 0
            if level < max_level:
                self._shed_level = level + 1
        elif level > 0:
            self._shed_clean += 1
            if self._shed_clean >= _SHED_CLEAN_EXITS:
                self._shed_clean = 0
                self._shed_level = level - 1
        if self._shed_level == level:
            return
        shed_lanes = list(
            self.priority_lanes[len(self.priority_lanes)
                                - self._shed_level:])
        self.metrics["shedding"].set(self._shed_level)
        self.obs.event("fleet_shed_level", level=self._shed_level,
                       was=level, lanes=shed_lanes)
        self._journal([(REC_ROUTE_SHED,
                        {"level": self._shed_level, "was": level,
                         "lanes": shed_lanes})])
        if self._shed_level > level:
            self._say(f"OVERLOADED: shedding priority tier(s) "
                      f"{','.join(shed_lanes) or '-'} "
                      f"(level {self._shed_level}/{max_level}) until "
                      "queue pressure clears")
        else:
            self._say(f"shed level down to {self._shed_level}"
                      f"/{max_level}"
                      + (f" (still shedding "
                         f"{','.join(shed_lanes)})" if shed_lanes
                         else " — admitting every tier again"))

    def _shed_check(self, priority) -> dict | None:
        """The admission-time half: a submit in one of the currently
        shed tiers is turned away with a truthful ``overloaded`` +
        ``retry_after_s`` BEFORE any member sees it.  None = admit."""
        level = self._shed_level
        if level <= 0 or not self.priority_lanes:
            return None
        lanes = self.priority_lanes      # highest tier first
        lane = str(priority or "") or lanes[-1]
        try:
            rank = lanes.index(lane)
        except ValueError:
            rank = len(lanes) - 1   # a lane no member configured
            #   carries no priority claim here — lowest tier
        if rank < len(lanes) - level:
            return None
        self.metrics["jobs"].inc(outcome="rejected")
        self.metrics["shed"].inc(lane=lane or "default")
        return protocol.err(
            protocol.ERR_OVERLOADED,
            f"fleet is overloaded: priority tier {lane!r} is being "
            f"shed (brownout level {level}/{len(lanes) - 1}) until "
            "queue pressure clears; no member was asked — retry "
            "after the suggested backoff or resubmit on a higher "
            "tier", lane=lane or "default",
            retry_after_s=round(1.0 + level, 1))

    def _members_by_depth(self) -> list[_Member]:
        """Alive members, least-loaded first: reported depth+running
        plus only the placements the member's LAST stats reply cannot
        have observed yet (``dispatched_since_poll`` — counting every
        live routed job here would double-count work the member
        already reports), round-robin on ties."""
        with self._lock:
            # fenced members are reachable but refusing work — they
            # get no placements until the next healthy poll re-grants
            # their lease (a fence is a pause, not a death)
            alive = [m for m in self.members.values()
                     if m.alive and not m.fenced]
            # quarantined members (gray failure, ISSUE 18) take no
            # NEW placements — but a slow member still beats no
            # member: with every live member quarantined, fall back
            # to them rather than wedge the fleet
            eligible = [m for m in alive if not m.quarantined]
            if eligible:
                alive = eligible
            self._rr += 1
            rr = self._rr
            order = sorted(
                enumerate(alive),
                key=lambda im: (im[1].queue_depth + im[1].running
                                + im[1].dispatched_since_poll,
                                (im[0] + rr) % max(1, len(alive))))
        return [m for _i, m in order]

    # ---- failover ------------------------------------------------------
    def _member_down(self, name: str) -> None:
        with self._lock:
            m = self.members.get(name)
            if m is None or not m.alive:
                return
            m.alive = False
            m.cache_enabled = None   # a member that rejoins may have
            #   been restarted WITH caching on — re-learn its verdict
            affected = [j for j in self.jobs.values()
                        if j.member == name and not j.retired
                        and j.terminal is None and j.scatter is None]
            # scattered m2m streams re-partition, never _recover_job:
            # the router itself holds their replay state per sub
            scattered = [j for j in self.jobs.values()
                         if j.scatter is not None and not j.retired
                         and j.terminal is None]
        self.failovers += 1
        self.metrics["failovers"].inc()
        self.metrics["member_up"].set(0, member=name)
        self.obs.event("member_down", member=name,
                       affected=len(affected))
        self._say(f"member {name} is DOWN ({len(affected)} routed "
                  "job(s) affected)")
        # fencing (ISSUE 16): bump the fleet epoch BEFORE any re-
        # placement so every re-admission below carries the new era —
        # if the "dead" member is actually a zombie (network blip,
        # stalled host), its lease expires without a heartbeat at the
        # new epoch and it self-fences before it can double-write
        with self._lock:
            self.epoch += 1
            epoch = self.epoch
        self.metrics["epoch"].set(epoch)
        self._journal([(REC_EPOCH,
                        {"epoch": epoch, "why": f"member_down:{name}"})])
        try:
            # best-effort synchronous fence: if the member is a
            # reachable zombie this lands instantly; a truly dead one
            # just refuses the connect
            with self._dial(m.target, timeout=1.0) as c:
                c.request({"cmd": "fence",
                           "reason": f"fleet failover epoch {epoch}: "
                           "the router declared this member dead"})
        except (ServiceError, OSError):
            pass
        folded: dict = {}
        if m.journal_path:
            try:
                records = JobJournal(m.journal_path).replay()
                folded = fold_records(records) if records else {}
            except Exception as e:
                self._say(f"warning: cannot read member journal "
                          f"{m.journal_path}: {e} — failing over "
                          "without it")
        for job in affected:
            self._recover_job(job, folded.get(job.mjid))
        for job in scattered:
            self._scatter_redrive(job, name)
        if folded and affected and m.journal_path:
            # set the consumed journal aside: a later restart of this
            # member must not replay jobs a sibling now owns (two
            # processes resuming the same report file is corruption,
            # not redundancy)
            try:
                from pwasm_tpu.utils.fsio import replace_durable
                replace_durable(m.journal_path,
                                m.journal_path + ".recovered")
                self.obs.event("journal_set_aside", member=name,
                               path=m.journal_path + ".recovered")
            except OSError as e:
                self._say(f"warning: cannot set aside {name}'s "
                          f"journal after failover ({e}); do NOT "
                          "restart the member on it")

    def _recover_job(self, job: _FleetJob,
                     row: dict | None = None) -> None:
        """One job's failover verdict (module docstring).  ``row`` is
        the folded journal state for this job; None means "resolve it
        yourself" — the method re-reads the dead member's journal so
        a caller WITHOUT the fold (a result-waiter rescuing an orphan
        the death snapshot missed) still gets the journal verdict: a
        bare resume-anyway would re-run a job whose finish (or acked
        cancel) is durably recorded.  Idempotent and race-safe: a
        per-job latch plus a live-member check make concurrent calls
        (health loop vs a result-waiter) no-ops — a job must never be
        re-admitted twice."""
        with self._lock:
            if job.terminal is not None or job.retired \
                    or job.recovering:
                return
            m = self.members.get(job.member)
            if m is not None and m.alive:
                return        # already re-placed on a live member
            job.recovering = True
            jp = m.journal_path if m is not None else None
        try:
            if row is None and jp:
                try:
                    records = JobJournal(jp).replay()
                    row = fold_records(records).get(job.mjid) \
                        if records else None
                except Exception:
                    row = None    # unreadable/set-aside journal:
                    #               the resume-anyway path is the
                    #               documented safe fallback
            self._recover_job_inner(job, row)
        finally:
            with self._lock:
                job.recovering = False

    def _deadline_left_ms(self, job: _FleetJob) -> int | None:
        """Remaining end-to-end budget of a routed job (None = no
        deadline): the budget at router arrival minus everything
        spent since, so re-placements never hand a member more time
        than the client has left."""
        if job.deadline_ms is None:
            return None
        return job.deadline_ms - int(
            (time.monotonic() - job.submitted_mono) * 1000.0)

    def _recover_job_inner(self, job: _FleetJob,
                           row: dict | None) -> None:
        dead = job.member
        # journal verdicts FIRST — a stream job whose finish (or
        # acked cancel) is durably recorded must be served, not told
        # to re-send records (the member's own restart replay orders
        # its checks the same way)
        fin = row.get("finish") if row else None
        if fin is not None:
            state = fin.get("state") \
                if fin.get("state") in TERMINAL_STATES else JOB_FAILED
            rc = fin.get("rc") if isinstance(fin.get("rc"), int) \
                else None
            extra: dict = {}
            spool = fin.get("spool")
            if isinstance(spool, dict) \
                    and isinstance(spool.get("path"), str):
                from pwasm_tpu.service.daemon import \
                    load_spool_payload
                payload, err = load_spool_payload(spool["path"])
                if payload is not None:
                    extra = {"stats": payload.get("stats"),
                             "stderr_tail":
                             str(payload.get("stderr_tail") or "")}
                else:
                    extra = {"spool_error": err}
            self._cache_terminal(job, state, rc,
                                 str(fin.get("detail") or "")
                                 + " [served from the dead member's "
                                 "journal+spool]", **extra)
            self.recovered["restored"] += 1
            self.metrics["recovered"].inc(how="restored")
            return
        if row is not None and row.get("cancel") is not None:
            self._cache_terminal(job, JOB_CANCELLED, None, (
                "cancel was acked before the member died; not re-run"))
            self.recovered["cancelled"] += 1
            self.metrics["recovered"].inc(how="cancelled")
            return
        if job.stream:
            # a LIVE-at-crash socket stream: first try the bounded
            # replay window (--stream-replay-bytes) — every acked
            # record is still buffered at the router, so a sibling
            # can be fed the whole prefix and the client never even
            # sees the death.  Past the window (or with it off) no
            # sibling can re-run the stream alone — terminal
            # preempted-resumable, the same verdict the member's own
            # restart replay reaches.
            if self._redrive_stream(job, dead):
                return
            self._cache_terminal(job, JOB_PREEMPTED, 75, (
                "stream interrupted: fleet member died; records up "
                "to the last checkpoint are durable — re-open a "
                "stream with --resume and re-send the records"))
            self.recovered["stream_preempted"] += 1
            self.metrics["recovered"].inc(how="stream_preempted")
            return
        # live at crash time: re-admit on a sibling.  With a journal
        # row, `start` tells us whether a --resume continuation is
        # needed; without one (per-daemon journal on an unreachable
        # host) --resume is still the safe choice — it resumes a valid
        # checkpoint when one exists and restarts cleanly when none
        # does.
        resume = row["start"] is not None if row is not None \
            else True
        argv = list(job.frame.get("args") or [])
        # fencing invariant (qa/check_supervision.py): every --resume
        # re-admission passes the epoch guard — a resume placed under
        # an older epoch than the job's current one would race the
        # newer owner on the same report file
        epoch = readmit_epoch_guard(job.epoch, self.epoch)
        if resume and "--resume" not in argv:
            argv = argv + ["--resume"]
        fwd = dict(job.frame, args=argv)
        left = self._deadline_left_ms(job)
        if left is not None:
            if left <= 0:
                # the budget died with the member: land the same
                # truthful verdict the member itself would have
                # reached — resumable, journal-honest, no sibling
                # burns a queue slot on an already-expired job
                self._cache_terminal(job, JOB_PREEMPTED, 75, (
                    "deadline_exceeded: the end-to-end budget was "
                    "already spent when its member died; work up to "
                    "the last durable checkpoint survives — "
                    "resubmit with --resume and a fresh "
                    "--deadline-s"))
                self.recovered["deadline_exceeded"] += 1
                self.metrics["recovered"].inc(how="deadline_exceeded")
                return
            fwd["deadline_ms"] = left
        placed = False
        for m in self._members_by_depth():
            if m.name == dead:
                continue
            try:
                c = self._dial(m.target, timeout=30.0)
            except ServiceError:
                continue       # connect refused: safe to try the next
            try:
                with c:
                    resp = c.request({
                        "cmd": "submit", **fwd,
                        "trace_id": job.trace_id,
                        "client": job.client,
                        **({"priority": job.priority}
                           if job.priority else {})})
            except ServiceError:
                # the frame may have been WRITTEN before the
                # connection died — the sibling could have admitted
                # the job without us seeing the ack.  At-most-once
                # (the same rule as _route_submit): land the job
                # terminal failed instead of re-admitting a possibly
                # duplicate copy on yet another sibling.
                self._cache_terminal(job, JOB_FAILED, None, (
                    f"failover re-admission to member {m.name} "
                    "failed mid-request; the job may or may not "
                    "have been admitted there, so it was not "
                    "retried elsewhere (at-most-once). Check that "
                    "member's results before resubmitting."))
                self.recovered["failed"] += 1
                self.metrics["recovered"].inc(how="failed")
                return
            if resp.get("ok"):
                with self._lock:
                    job.member = m.name
                    job.mjid = resp["job_id"]
                    job.gen += 1
                    job.epoch = epoch
                    job.failovers += 1
                    m.jobs_routed += 1
                    m.dispatched_since_poll += 1
                self.ledger.move(job.client, dead, m.name)
                self._journal([(REC_ROUTE_PLACE,
                                {"job_id": job.fid, "member": m.name,
                                 "mjid": job.mjid, "gen": job.gen,
                                 "epoch": epoch})])
                how = "resumed" if resume else "requeued"
                self.recovered[how] += 1
                self.metrics["recovered"].inc(how=how)
                self.obs.event("failover_readmit", job_id=job.fid,
                               trace_id=job.trace_id, member=m.name,
                               resumed=resume, was=dead)
                self._say(f"job {job.fid}: "
                          + ("resumed on" if resume
                             else "re-queued to")
                          + f" member {m.name}")
                placed = True
                break
        if not placed:
            self._cache_terminal(job, JOB_FAILED, None, (
                "fleet member died and no sibling could take the "
                "job over; resubmit (with --resume if a checkpoint "
                "exists)"))
            self.recovered["failed"] += 1
            self.metrics["recovered"].inc(how="failed")

    def _redrive_stream(self, job: _FleetJob, dead: str) -> bool:
        """Invisible mid-stream failover (ISSUE 16): re-open the
        stream on a sibling and re-drive every buffered (acked)
        record from the bounded replay window.  True = the job now
        lives on the sibling and the client's next frame forwards
        there as if nothing happened; False = no window (overflowed /
        disabled) or no sibling could take it — the caller lands the
        documented preempted-resumable verdict."""
        with self._lock:
            frames = list(job.rbuf) if job.rbuf is not None else None
            ended = job.ended
        if frames is None:
            return False
        left = self._deadline_left_ms(job)
        if left is not None and left <= 0:
            return False   # budget spent: the caller's preempted-
            #   resumable verdict is the truthful answer, and a
            #   sibling would refuse the expired admission anyway
        epoch = readmit_epoch_guard(job.epoch, self.epoch)
        for m in self._members_by_depth():
            if m.name == dead:
                continue
            try:
                c = self._dial(m.target, timeout=60.0)
            except ServiceError:
                continue
            try:
                resp = c.request({
                    "cmd": "stream", **job.frame,
                    "client": job.client,
                    **({"deadline_ms": left}
                       if left is not None else {}),
                    **({"trace_id": job.trace_id}
                       if job.trace_id else {}),
                    **({"priority": job.priority}
                       if job.priority else {})})
                if not resp.get("ok"):
                    c.close()
                    continue
                mjid = resp["job_id"]
                for f in frames:
                    fwd = dict(f)
                    fwd["job_id"] = mjid
                    for _retry in range(50):
                        r = c.request(fwd)
                        if r.get("error") == protocol.ERR_QUEUE_FULL:
                            time.sleep(min(0.2, float(
                                r.get("retry_after_s") or 0.1)))
                            continue
                        break
                    if not r.get("ok"):
                        raise ServiceError(
                            f"redrive rejected: {r.get('detail')}")
                if ended:
                    r = c.request({"cmd": "stream-end",
                                   "job_id": mjid})
                    if not r.get("ok"):
                        raise ServiceError(
                            f"redrive end rejected: {r.get('detail')}")
            except (ServiceError, OSError, KeyError, TypeError) as e:
                # at-most-once: the sibling may hold a half-fed
                # stream — cancel it best-effort, then fall back to
                # the preempted verdict rather than trying a THIRD
                # member with unknown state on the second
                try:
                    c.request({"cmd": "cancel",
                               "job_id": locals().get("mjid", "")})
                except (ServiceError, OSError):
                    pass
                c.close()
                self._say(f"stream {job.fid}: replay to {m.name} "
                          f"failed ({e}); landing preempted")
                return False
            with self._lock:
                old, job.sconn = job.sconn, c
                job.member = m.name
                job.mjid = mjid
                job.gen += 1
                job.epoch = epoch
                job.failovers += 1
                m.jobs_routed += 1
                m.dispatched_since_poll += 1
            if old is not None:
                old.close()
            self.ledger.move(job.client, dead, m.name)
            self._journal([(REC_ROUTE_PLACE,
                            {"job_id": job.fid, "member": m.name,
                             "mjid": mjid, "gen": job.gen,
                             "epoch": epoch})])
            self.recovered["stream_replayed"] += 1
            self.metrics["recovered"].inc(how="stream_replayed")
            self.obs.event("stream_redriven", job_id=job.fid,
                           trace_id=job.trace_id, member=m.name,
                           frames=len(frames), was=dead)
            self._say(f"stream {job.fid}: re-drove {len(frames)} "
                      f"buffered frame(s) to member {m.name} — "
                      "failover invisible to the client")
            return True
        return False

    def _cache_terminal(self, job: _FleetJob, state: str,
                        rc: int | None, detail: str,
                        **extra) -> None:
        resp = protocol.ok(
            job={"id": job.fid, "state": state, "rc": rc,
                 "detail": detail, "client": job.client,
                 "priority": job.priority, "trace_id": job.trace_id,
                 "stream": job.stream, "recovered": True,
                 "member": job.member,
                 "submitted_s": round(job.submitted_s, 3),
                 "started_s": None, "finished_s":
                 round(time.time(), 3)},
            rc=rc, stats=extra.pop("stats", None),
            stderr_tail=extra.pop("stderr_tail", ""), **extra)
        with self._lock:
            job.terminal = resp
        self.obs.event("failover_verdict", job_id=job.fid,
                       trace_id=job.trace_id, state=state, rc=rc)
        self._note_retired(job)

    # ---- protocol ------------------------------------------------------
    def _handle_conn(self, conn: socket.socket) -> None:
        from pwasm_tpu.service.daemon import _peer_identity
        if self.tls is not None and conn.family != socket.AF_UNIX:
            # handshake in THIS connection's thread; a failure is
            # counted and answered with a loud close, never a hang
            # or an accept-loop crash (same contract as the daemon)
            from pwasm_tpu.fleet.transport import server_handshake
            conn = server_handshake(conn, self.tls,
                                    on_failure=self._tls_failed)
            if conn is None:
                return
        protocol.serve_connection(conn, self._dispatch,
                                  peer=_peer_identity(conn),
                                  max_frame_bytes=self.max_frame_bytes)

    def _tls_failed(self, exc: Exception) -> None:
        self.transport_metrics["tls_handshake_failures"].inc()
        self.obs.event("tls_handshake_failed",
                       detail=f"{type(exc).__name__}: {exc}")

    def _dial(self, target: str, timeout: float | None = None,
              **kw) -> ServiceClient:
        """EVERY router->member connection is minted here, so the
        member-facing TLS config and capability token cannot be
        missed by one call site — an all-TLS fleet stays all-TLS
        through failover, cache probes, and scaler retires."""
        if self.member_token is not None:
            kw.setdefault("client_token", self.member_token)
        return ServiceClient(target, timeout=timeout,
                             tls=self.member_tls, **kw)

    def _resolve_client(self, req: dict, peer: str | None) -> str:
        """protocol.resolve_client_identity — shared with the serve
        daemon so router quota buckets and member DRR buckets cannot
        drift."""
        return protocol.resolve_client_identity(req, peer)

    def _auth_label(self, client: str) -> str:
        if client in self._auth_labels or len(self._auth_labels) < 64:
            self._auth_labels.add(client)
            return client
        return "other"

    def _authorize(self, cmd, req: dict, peer) -> dict | None:
        """The router-edge scoped-token gate (ISSUE 19) — the same
        policy shape as the member's: None = proceed, else the
        truthful `unauthorized` frame, with no ledger/journal state
        touched and no frame forwarded to any member."""
        from pwasm_tpu.service import authz
        scope = authz.required_scope(cmd, req)
        ok = False
        if scope is None or self.auth.allows(req, peer,
                                             authz.SCOPE_ADMIN):
            ok = True
        elif scope == authz.SCOPE_CANCEL_OWN:
            if self.auth.allows(req, peer, scope):
                job = self.jobs.get(req.get("job_id"))
                ok = (job is None or job.client
                      == self._resolve_client(req, peer))
        else:
            ok = self.auth.allows(req, peer, scope)
        key = peer or self._resolve_client(req, peer) or "anonymous"
        if ok:
            self._penalty.clear(key)
            return None
        client = self._resolve_client(req, peer) or "anonymous"
        self.transport_metrics["auth_failures"].inc(
            client=self._auth_label(client))
        self.obs.event("unauthorized", cmd=cmd, client=client)
        time.sleep(self._penalty.fail(key))
        return protocol.err(
            protocol.ERR_UNAUTHORIZED,
            f"cmd {cmd!r} requires scope {scope!r} and the presented "
            "credentials do not grant it (token file: "
            f"{self.auth.path})")

    def _dispatch(self, req: dict, peer: str | None = None) -> dict:
        cmd = req.get("cmd")
        if self.auth is not None:
            deny = self._authorize(cmd, req, peer)
            if deny is not None:
                return deny
        if self.rate_limiter is not None \
                and cmd in ("submit", "stream"):
            # edge rate limiting in front of the fleet ledger: a
            # refused frame reaches no member and writes no journal
            client = self._resolve_client(req, peer)
            wait = self.rate_limiter.admit(client or "default")
            if wait > 0:
                self.obs.event("rate_limited",
                               client=client or "default",
                               retry_after_s=wait)
                return protocol.err(
                    protocol.ERR_OVERLOADED,
                    f"rate limit: client "
                    f"{client or 'default'} exceeded "
                    f"{self.rate_limiter.rate:g}/s "
                    f"(burst {self.rate_limiter.burst:g})",
                    client=client or "default",
                    retry_after_s=wait)
        if cmd == "ping":
            with self._lock:
                alive = sum(1 for m in self.members.values()
                            if m.alive)
            return protocol.ok(
                protocol_version=protocol.PROTOCOL_VERSION,
                draining=self._draining, router=True,
                members=len(self.members), members_alive=alive)
        if cmd in ("submit", "stream"):
            return self._route_submit(req, peer,
                                      stream=(cmd == "stream"))
        if cmd in ("stream-data", "stream-end"):
            return self._route_stream_frame(req)
        if cmd == "stats":
            # refresh synchronously: svc-stats (and the fleet-aware
            # top built on it) must describe NOW, not the last poll
            self._poll_members()
            return protocol.ok(stats=self._fleet_stats())
        if cmd == "metrics":
            self._refresh_gauges()
            return protocol.ok(
                metrics=self.registry.expose(
                    exemplars=bool(req.get("exemplars"))),
                content_type="text/plain; version=0.0.4")
        if cmd == "health":
            return protocol.ok(health=self._fleet_health())
        if cmd == "logs":
            return protocol.handle_logs(req, self.log_json_path)
        if cmd == "drain":
            self.drain.request("drain requested by client")
            self._begin_drain(self.drain.reason)
            with self._lock:
                live = sorted(j.fid for j in self.jobs.values()
                              if not j.retired and j.terminal is None)
            return protocol.ok(draining=True, running=live,
                               preempted_queued=[])
        if cmd in ("status", "result", "cancel", "inspect"):
            job = self.jobs.get(req.get("job_id"))
            if job is None:
                # unknown OR evicted past max_results: same answer
                return protocol.err(
                    protocol.ERR_UNKNOWN_JOB,
                    f"unknown job_id {req.get('job_id')!r}")
            job.accessed_s = time.time()   # the LRU clock
            if cmd == "result":
                return self._route_result(job, req)
            return self._route_simple(job, cmd)
        return protocol.err(protocol.ERR_UNKNOWN_CMD,
                            f"unknown cmd {cmd!r}")

    def _route_submit(self, req: dict, peer: str | None,
                      stream: bool) -> dict:
        t_in = time.monotonic()   # the deadline decrement anchor:
        #   every millisecond this frame spends inside the router
        #   (cache probe, affinity pass, placement retries) comes out
        #   of the client's end-to-end budget before a member sees it
        if self._draining:
            return protocol.err(protocol.ERR_DRAINING,
                                "fleet router is draining")
        client = self._resolve_client(req, peer)
        if not isinstance(client, str) or len(client) > 64:
            return protocol.err(protocol.ERR_BAD_REQUEST,
                                "client must be a short identifier")
        deadline_ms, dl_err = protocol.parse_deadline_ms(req)
        if dl_err is not None:
            return dl_err
        shed = self._shed_check(req.get("priority"))
        if shed is not None:
            self.obs.event("route_shed", client=client or "default",
                           lane=str(req.get("priority") or "")
                           or "default")
            return shed
        trace_id = req.get("trace_id")
        frame = {"args": req.get("args"), "cwd": req.get("cwd")}
        if req.get("priority") is not None:
            frame["priority"] = req.get("priority")
        if stream and req.get("delta"):
            # delta-over-stream opt-in (docs/STREAMING.md) rides the
            # member stream-open — and, because it lives in the
            # journaled frame, every failover re-open too
            frame["delta"] = True
        # fleet result cache (ISSUE 15): consult the shared cache dir
        # at the router's edge — a hit never reaches a member
        cache_key_hex = None
        cache_family = None
        if self.cache is not None and not stream:
            cache_key_hex, cache_family, served = self._cache_lookup(
                frame, client, req.get("priority"), trace_id)
            if served is not None:
                return served
        order = self._members_by_depth()
        if not order:
            return protocol.err(
                protocol.ERR_QUEUE_FULL,
                "no live fleet members (retry after they rejoin)",
                retry_after_s=2.0)
        if stream and len(order) > 1 \
                and self._scatter_eligible(frame):
            # fleet-wide m2m surveillance (ISSUE 20): partition the
            # target stream across the members; None = could not hold
            # two sub-streams open, fall back to one member
            out = self._scatter_submit(req, frame, client, trace_id,
                                       deadline_ms, t_in, order)
            if out is not None:
                return out
            order = self._members_by_depth()
            if not order:
                return protocol.err(
                    protocol.ERR_QUEUE_FULL,
                    "no live fleet members (retry after they rejoin)",
                    retry_after_s=2.0)
        if cache_key_hex is not None and len(order) > 1:
            # miss at the router: cache-AFFINITY placement — a member
            # whose private cache holds the key gets the job (its own
            # admission serves it), so the fleet never re-runs a job
            # ANY member has already answered.  A member holding only
            # the job's FAMILY (a near-repeat prefix) ranks next: its
            # admission answers the job as a delta (ISSUE 17c)
            order = self._cache_affinity(order, cache_key_hex,
                                         cache_family)
        last_reject: dict | None = None
        for m in order:
            fwd_deadline: dict = {}
            if deadline_ms is not None:
                # remaining-budget arithmetic: subtract the time this
                # frame has already spent inside the router before
                # handing the member what is genuinely left
                rem = deadline_ms - int(
                    (time.monotonic() - t_in) * 1000.0)
                if rem <= 0:
                    self.metrics["jobs"].inc(outcome="rejected")
                    return protocol.err(
                        protocol.ERR_DEADLINE_EXCEEDED,
                        "end-to-end deadline budget "
                        f"({deadline_ms} ms at the router) was spent "
                        "in routing before any member admitted the "
                        "job — nothing was admitted; resubmit with a "
                        "fresh --deadline-s", deadline_ms=rem)
                fwd_deadline = {"deadline_ms": rem}
            try:
                self.ledger.admit(client, m.name)
            except QueueFull as e:
                self.metrics["jobs"].inc(outcome="rejected")
                self.obs.event("route_reject", client=client,
                               detail=str(e))
                return protocol.err(
                    protocol.ERR_QUEUE_FULL, str(e),
                    client=client or "default",
                    client_depth=self.ledger.client_depths().get(
                        client, 0),
                    retry_after_s=2.0)
            t0 = self.obs.tracer.now() \
                if self.obs.tracer is not None else 0.0
            try:
                c = self._dial(m.target, timeout=60.0)
            except ServiceError:
                self.ledger.retire(client, m.name)
                self._member_down(m.name)
                continue
            try:
                resp = c.request({
                    "cmd": "stream" if stream else "submit",
                    **frame, "client": client, **fwd_deadline,
                    **({"trace_id": trace_id}
                       if isinstance(trace_id, str) and trace_id
                       else {})})
            except ServiceError:
                # the frame may have been WRITTEN before the
                # connection died: the member could have admitted
                # (and journaled) the job even though we never saw
                # the ack.  Re-placing it on a sibling here would be
                # a possible double admission — two processes running
                # the same -o argv, the corruption this router's own
                # failover logic refuses elsewhere.  At-most-once
                # wins: fail the submission loudly instead.  (A
                # CONNECT-phase failure above carries no such risk
                # and does try the next sibling.)
                c.close()
                self.ledger.retire(client, m.name)
                self._member_down(m.name)
                self.metrics["jobs"].inc(outcome="rejected")
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    f"fleet member {m.name} failed mid-submission; "
                    "the job may or may not have been admitted "
                    "there, so it was NOT retried on a sibling "
                    "(at-most-once). Check the member's "
                    "journal/results before resubmitting.")
            if self.obs.tracer is not None:
                self.obs.tracer.complete(
                    "route_submit", t0, trace_id=trace_id,
                    member=m.name)
            if resp.get("ok"):
                with self._lock:
                    self._next_id += 1
                    fid = f"fleet-{self._next_id:04d}"
                    job = _FleetJob(fid, client,
                                    str(req.get("priority") or ""),
                                    str(resp.get("trace_id")
                                        or trace_id or ""),
                                    frame, m.name, resp["job_id"],
                                    stream=stream)
                    job.epoch = self.epoch
                    if deadline_ms is not None:
                        # anchor at frame ARRIVAL, not placement —
                        # a failover re-admission must forward what
                        # is left of the CLIENT's budget, and the
                        # routing time above already spent some
                        job.deadline_ms = deadline_ms
                        job.submitted_mono = t_in
                    if stream:
                        job.sconn = c
                        if self.stream_replay_bytes <= 0:
                            job.rbuf = None   # replay window off
                    self.jobs[fid] = job
                    m.jobs_routed += 1
                    m.dispatched_since_poll += 1
                if not stream:
                    c.close()
                # WAL: the client's ack and this pair commit together
                # (one fsync) — an admission the journal missed was
                # never acked, so replay can safely drop it
                self._journal([
                    (REC_ROUTE_ADMIT,
                     {"job_id": fid, "client": client,
                      "priority": job.priority,
                      "trace_id": job.trace_id, "stream": stream,
                      "frame": frame}),
                    (REC_ROUTE_PLACE,
                     {"job_id": fid, "member": m.name,
                      "mjid": job.mjid, "gen": 0,
                      "epoch": job.epoch})])
                self.metrics["jobs"].inc(outcome="accepted")
                self.metrics["routed"].inc(member=m.name)
                self.obs.event("route_admit", job_id=fid,
                               member=m.name, client=client,
                               stream=stream,
                               trace_id=job.trace_id)
                out = dict(resp)
                out["job_id"] = fid
                out["member"] = m.name
                return out
            c.close()
            self.ledger.retire(client, m.name)
            if resp.get("error") in (protocol.ERR_QUEUE_FULL,
                                     protocol.ERR_FENCED):
                # queue_full: try the next-best sibling.  fenced: the
                # member lost its lease between our poll and this
                # frame — same treatment (the poll will mark it)
                last_reject = resp
                continue
            # bad_request / draining etc: the member's diagnostic is
            # the authoritative one — relay it
            self.metrics["jobs"].inc(outcome="rejected")
            return resp
        self.metrics["jobs"].inc(outcome="rejected")
        return last_reject if last_reject is not None else \
            protocol.err(protocol.ERR_QUEUE_FULL,
                         "every fleet member is at capacity",
                         retry_after_s=2.0)

    def _cache_lookup(self, frame: dict, client: str, priority,
                      trace_id
                      ) -> tuple[str | None, str | None, dict | None]:
        """``(key, family, terminal-submit-response | None)``: derive
        the content-addressed key from the cwd-absolutized argv and
        consult the router's shared cache dir.  A hit writes the
        verified output bytes to the job's own output paths and
        answers a terminal fleet job on the spot — zero members, zero
        queues, zero devices.  Any defect falls through to a normal
        placement (the key and its delta FAMILY, when derivable,
        still feed affinity)."""
        from pwasm_tpu.service.cache import (argv_stats_path,
                                             classify_argv,
                                             derive_keys,
                                             serve_outputs,
                                             write_hit_stats)
        from pwasm_tpu.service.daemon import _absolutize_argv
        args = frame.get("args")
        if not isinstance(args, list) \
                or not all(isinstance(a, str) for a in args):
            return None, None, None
        argv = list(args)
        cwd = frame.get("cwd")
        if isinstance(cwd, str) and os.path.isabs(cwd):
            argv = _absolutize_argv(argv, cwd)
        cls = classify_argv(argv)
        derived = derive_keys(cls) if cls is not None else None
        if derived is None:
            return None, None, None
        key, family = derived
        got = self.cache.get(key)
        if got is None:
            return key, family, None
        manifest, blobs = got
        try:
            if not serve_outputs(blobs, cls.output_paths):
                return key, family, None
        except OSError:
            return key, family, None   # unwritable outputs: let a
            #                     member produce the real diagnostic
        stats = write_hit_stats(manifest, argv_stats_path(argv))
        with self._lock:
            self._next_id += 1
            fid = f"fleet-{self._next_id:04d}"
            job = _FleetJob(fid, client, str(priority or ""),
                            str(trace_id or ""), dict(frame),
                            "cache", "", stream=False)
            job.retired = True      # never entered the ledger
            self.jobs[fid] = job
        resp = protocol.ok(
            job={"id": fid, "state": "done", "rc": 0,
                 "detail": "served from the fleet result cache "
                           "(byte-identical to a full run)",
                 "client": client, "priority": job.priority,
                 "trace_id": job.trace_id, "stream": False,
                 "recovered": False, "member": "cache",
                 "submitted_s": round(job.submitted_s, 3),
                 "started_s": None,
                 "finished_s": round(time.time(), 3)},
            rc=0, stats=stats, stderr_tail="")
        with self._lock:
            job.terminal = resp
        self._journal([
            (REC_ROUTE_ADMIT,
             {"job_id": fid, "client": client,
              "priority": job.priority, "trace_id": job.trace_id,
              "stream": False, "frame": dict(frame),
              "cache_hit": True}),
            (REC_ROUTE_RETIRE,
             {"job_id": fid, "state": "done", "rc": 0,
              "detail": "served from the fleet result cache "
                        "(byte-identical to a full run)"})])
        self.metrics["jobs"].inc(outcome="accepted")
        self.obs.event("cache_hit", job_id=fid,
                       trace_id=job.trace_id)
        return key, family, protocol.ok(
            job_id=fid, trace_id=job.trace_id,
            member="cache", cache_hit=True, queue_depth=0)

    def _cache_affinity(self, order: list, key: str,
                        family: str | None = None) -> list:
        """Reorder placement so the first member whose ``cache-probe``
        answers hit=true goes first; with an exact hit nowhere, the
        first member answering family_hit=true (it holds a same-family
        entry, so its admission can serve the job as a DELTA) fronts
        instead — the router learns delta verdicts the same way it
        learns exact ones.  The probe is a placement HINT, never worth
        stalling admission for: per-probe timeout is short, the WHOLE
        pass is budgeted (~1s), a member that answered enabled=false
        is skipped until it next rejoins (``_member_down`` resets the
        verdict), and probe failures are never death evidence."""
        deadline = time.monotonic() + 1.0
        family_m = None
        for m in order:
            if m.cache_enabled is False:
                continue
            if time.monotonic() >= deadline:
                break            # a hint must not gate the submit
            probe = {"cmd": "cache-probe", "key": key}
            if family is not None:
                probe["family"] = family
            try:
                with self._dial(m.target, timeout=0.5) as c:
                    r = c.request(probe)
            except ServiceError:
                continue
            if not r.get("ok"):
                continue
            m.cache_enabled = bool(r.get("enabled"))
            if r.get("hit"):
                return [m] + [x for x in order if x is not m]
            if family_m is None and r.get("family_hit"):
                family_m = m
        if family_m is not None:
            return [family_m] + [x for x in order
                                 if x is not family_m]
        return order

    def _route_stream_frame(self, req: dict) -> dict:
        job = self.jobs.get(req.get("job_id"))
        if job is None:
            return protocol.err(
                protocol.ERR_UNKNOWN_JOB,
                f"unknown job_id {req.get('job_id')!r}")
        if not job.stream:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"job {job.fid} is not a stream job")
        if job.scatter is not None:
            job.accessed_s = time.time()
            return self._scatter_stream_frame(job, req)
        with self._lock:
            # snapshot under the lock: _note_retired pops job.sconn
            # concurrently (a stream that landed terminal server-side
            # while the client was still pumping frames)
            sconn = job.sconn
            closed = job.terminal is not None or job.retired \
                or sconn is None
        if closed:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"stream {job.fid} is closed; re-open a stream with "
                "--resume to complete it")
        fwd = dict(req)
        fwd["job_id"] = job.mjid
        try:
            with job.slock:
                resp = sconn.request(fwd)
            if resp.get("ok"):
                self._buffer_stream_frame(job, req)
            return resp
        except ServiceError:
            # decide WHOSE failure this was before declaring a member
            # dead: a router-side close (the job retired mid-request)
            # is a closed stream on a healthy member, and failing the
            # member over for it would re-run jobs it still owns
            with self._lock:
                retired_now = job.retired or job.terminal is not None
                gen = job.gen
            if retired_now:
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    f"stream {job.fid} is closed; re-open a stream "
                    "with --resume to complete it")
            self._member_down(job.member)
            # _member_down runs failover synchronously: if the replay
            # window re-drove this stream to a sibling, forward THIS
            # frame there too — the client never learns anything died
            with self._lock:
                moved = job.gen != gen and job.terminal is None \
                    and not job.retired
                sconn2, mjid2 = job.sconn, job.mjid
            if moved and sconn2 is not None:
                fwd2 = dict(req)
                fwd2["job_id"] = mjid2
                try:
                    with job.slock:
                        resp = sconn2.request(fwd2)
                    if resp.get("ok"):
                        self._buffer_stream_frame(job, req)
                    return resp
                except ServiceError:
                    pass     # the sibling died too: fall through
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"stream {job.fid} lost its member mid-stream; "
                "re-open a stream with --resume and re-send the "
                "records")

    def _buffer_stream_frame(self, job: _FleetJob, req: dict) -> None:
        """Append one ACKED stream frame to the job's bounded replay
        window.  Past --stream-replay-bytes the window is dropped
        (not truncated — a partial prefix replays a corrupt stream)
        and mid-stream failover degrades to the documented
        preempted-resumable verdict."""
        with self._lock:
            if req.get("cmd") == "stream-end":
                job.ended = True
                return
            if job.rbuf is None:
                return
            data = req.get("data")
            size = len(data) if isinstance(data, str) else 256
            if job.rbytes + size > self.stream_replay_bytes:
                job.rbuf = None
                job.rbytes = 0
                fid = job.fid
            else:
                job.rbuf.append(dict(req))
                job.rbytes += size
                return
        self.obs.event("stream_window_overflow", job_id=fid,
                       limit=self.stream_replay_bytes)

    # ---- fleet-wide m2m scatter (ISSUE 20) -----------------------------
    # A --m2m-stream opened against the router with >= 2 live members
    # is PARTITIONED, not placed: one sub-stream per member, arriving
    # target records dealt round-robin (surveil/partition.ScatterState
    # keeps the arrival-order bookkeeping), per-sub replay buffers so
    # a member death re-partitions its records wholesale onto a
    # survivor, and the per-member section fragments spliced back into
    # ONE report at result time — byte-identical to an un-scattered
    # run over the same stream.  All scatter state lives under
    # sc["lock"] (an RLock: a send failure inside a frame handler
    # re-enters via _member_down -> _scatter_redrive); member sub
    # connections are only ever used under that lock.

    @staticmethod
    def _scatter_eligible(frame: dict) -> bool:
        args = frame.get("args")
        return (isinstance(args, list)
                and all(isinstance(a, str) for a in args)
                and "--m2m-stream" in args and "-o" in args)

    def _scatter_submit(self, req: dict, frame: dict, client: str,
                        trace_id, deadline_ms, t_in: float,
                        order: list) -> dict | None:
        """Open one sub-stream per live member; None = fewer than two
        stayed open (the caller falls back to a single placement)."""
        from pwasm_tpu.surveil.partition import (ScatterState,
                                                 rewrite_out_args)
        from pwasm_tpu.surveil.records import FastaAssembler
        args = [str(a) for a in frame.get("args") or []]
        cwd = frame.get("cwd")
        cwd = cwd if isinstance(cwd, str) and cwd else os.getcwd()

        def _abspath(p):
            return p if os.path.isabs(p) else os.path.join(cwd, p)

        o = s = stats_path = None
        i = 0
        while i < len(args):
            a = args[i]
            if a == "-o" and i + 1 < len(args):
                o = args[i + 1]
                i += 2
                continue
            if a == "-s" and i + 1 < len(args):
                s = args[i + 1]
                i += 2
                continue
            if a.startswith("--stats="):
                stats_path = a[len("--stats="):]
            i += 1
        if not o:
            return None
        o = _abspath(o)
        s = _abspath(s) if s else None
        stats_path = _abspath(stats_path) if stats_path else None
        rem = None
        if deadline_ms is not None:
            rem = deadline_ms - int((time.monotonic() - t_in) * 1000.0)
            if rem <= 0:
                self.metrics["jobs"].inc(outcome="rejected")
                return protocol.err(
                    protocol.ERR_DEADLINE_EXCEEDED,
                    f"end-to-end deadline budget ({deadline_ms} ms "
                    "at the router) was spent in routing before any "
                    "member admitted the scattered stream; resubmit "
                    "with a fresh --deadline-s", deadline_ms=rem)
        state = ScatterState()
        subs: list = []
        ntag = 0
        for m in order:
            # the fragment tag burns per ATTEMPT, not per success: a
            # mid-request open failure may have left a ghost sub job
            # writing to this tag's paths — never reuse them
            frag_o = f"{o}.frag{ntag:02d}"
            frag_s = f"{s}.frag{ntag:02d}" if s else None
            ntag += 1
            sargs = rewrite_out_args(args, o=frag_o, s=frag_s)
            row = self._scatter_open_sub(
                m, sargs, cwd, client, trace_id, rem,
                frame.get("priority"))
            if row is None:
                continue
            state.add_sub()
            row["o"], row["s"] = frag_o, frag_s
            subs.append(row)
        if len(subs) < 2:
            for r in subs:
                self._scatter_cancel_sub(r)
            return None
        try:
            self.ledger.admit(client, subs[0]["member"])
        except QueueFull as e:
            for r in subs:
                self._scatter_cancel_sub(r)
            self.metrics["jobs"].inc(outcome="rejected")
            self.obs.event("route_reject", client=client,
                           detail=str(e))
            return protocol.err(
                protocol.ERR_QUEUE_FULL, str(e),
                client=client or "default",
                client_depth=self.ledger.client_depths().get(
                    client, 0),
                retry_after_s=2.0)
        with self._lock:
            self._next_id += 1
            fid = f"fleet-{self._next_id:04d}"
            job = _FleetJob(fid, client,
                            str(req.get("priority") or ""),
                            str(trace_id or ""), frame,
                            subs[0]["member"], subs[0]["mjid"],
                            stream=True)
            job.epoch = self.epoch
            if deadline_ms is not None:
                job.deadline_ms = deadline_ms
                job.submitted_mono = t_in
            job.rbuf = None   # the scatter keeps RECORD-granular
            #   replay buffers per sub instead of the frame window
            job.scatter = {
                "lock": threading.RLock(), "state": state,
                "subs": subs, "asm": FastaAssembler(), "o": o,
                "s": s, "stats_path": stats_path, "args": args,
                "cwd": cwd, "ntag": ntag,
                "texts": [[] for _ in subs], "rbytes": 0,
                "ended": False}
            if self.stream_replay_bytes <= 0:
                job.scatter["texts"] = None
            self.jobs[fid] = job
            for r in subs:
                m = self.members.get(r["member"])
                if m is not None:
                    m.jobs_routed += 1
                    m.dispatched_since_poll += 1
        rows = [(REC_ROUTE_ADMIT,
                 {"job_id": fid, "client": client,
                  "priority": job.priority, "trace_id": job.trace_id,
                  "stream": True, "frame": frame, "scatter": True})]
        for k, r in enumerate(subs):
            rows.append((REC_ROUTE_PLACE,
                         {"job_id": fid, "member": r["member"],
                          "mjid": r["mjid"], "gen": 0,
                          "epoch": job.epoch, "sub": k}))
        self._journal(rows)
        self.metrics["jobs"].inc(outcome="accepted")
        for r in subs:
            self.metrics["routed"].inc(member=r["member"])
        self.obs.event("scatter_admit", job_id=fid, client=client,
                       subs=len(subs), trace_id=job.trace_id,
                       members=",".join(r["member"] for r in subs))
        self._say(f"stream {fid}: scattered --m2m-stream across "
                  f"{len(subs)} member(s)")
        out = dict(subs[0].pop("open"))
        for r in subs[1:]:
            r.pop("open", None)
        out["job_id"] = fid
        out["member"] = f"scatter[{len(subs)}]"
        out["scatter"] = [r["member"] for r in subs]
        return out

    def _scatter_open_sub(self, m, sargs: list, cwd: str,
                          client: str, trace_id, rem,
                          priority) -> dict | None:
        try:
            c = self._dial(m.target, timeout=60.0)
        except ServiceError:
            self._member_down(m.name)
            return None
        reqd: dict = {"cmd": "stream", "args": sargs, "cwd": cwd,
                      "client": client}
        if priority:
            reqd["priority"] = priority
        if rem is not None:
            reqd["deadline_ms"] = rem
        if isinstance(trace_id, str) and trace_id:
            reqd["trace_id"] = trace_id
        try:
            resp = c.request(reqd)
        except ServiceError:
            # mid-request failure: the member may hold a ghost sub
            # stream — its fragment tag is burned (never reused) and
            # its idle reaper will collect the ghost, so skipping the
            # member is safe where the un-scattered path must abort
            c.close()
            self._member_down(m.name)
            return None
        if not resp.get("ok"):
            c.close()
            return None
        return {"member": m.name, "mjid": resp["job_id"], "conn": c,
                "live": True, "open": resp}

    @staticmethod
    def _scatter_cancel_sub(row: dict) -> None:
        try:
            row["conn"].request({"cmd": "cancel",
                                 "job_id": row["mjid"]})
        except ServiceError:
            pass
        row["conn"].close()
        row["live"] = False

    def _scatter_stream_frame(self, job: _FleetJob,
                              req: dict) -> dict:
        sc = job.scatter
        with self._lock:
            closed = job.terminal is not None or job.retired
        if closed or sc["ended"]:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"stream {job.fid} is closed; re-open a stream with "
                "--resume to complete it")
        with sc["lock"]:
            if req.get("cmd") == "stream-end":
                err = self._scatter_end(job)
                if err is not None:
                    return err
                sc["ended"] = True
                with self._lock:
                    job.ended = True
                return protocol.ok(records=sc["state"].nrec,
                                   buffered=0)
            data = req.get("data")
            if not isinstance(data, str):
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    "stream-data needs a string data field")
            if data == "":
                # keepalive: fan out so no member's idle reaper
                # mistakes a slow producer for a vanished client
                k = 0
                while k < len(sc["subs"]):
                    row = sc["subs"][k]
                    k += 1
                    if not row["live"]:
                        continue
                    err = self._scatter_send(job, row,
                                             {"cmd": "stream-data",
                                              "data": ""})
                    if err is not None:
                        return err
                return protocol.ok(records=sc["state"].nrec,
                                   buffered=0)
            for text in sc["asm"].feed(data):
                err = self._scatter_record(job, text)
                if err is not None:
                    return err
            return protocol.ok(records=sc["state"].nrec, buffered=0)

    def _scatter_record(self, job: _FleetJob, text: str
                        ) -> dict | None:
        """Deal one assembled target record to its sub-stream.  The
        record is BUFFERED before it is sent: a member death mid-send
        re-partitions it from the buffer, so frames never need a
        client resend (backpressure is the router blocking the ack)."""
        sc = job.scatter
        try:
            _gidx, sub = sc["state"].assign()
        except ValueError:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"stream {job.fid}: no live fleet members left for "
                "the scattered stream")
        if sc["texts"] is not None:
            sc["texts"][sub].append(text)
            sc["rbytes"] += len(text)
            if sc["rbytes"] > self.stream_replay_bytes:
                sc["texts"] = None   # window overflow: a member
                #   death now degrades to preempted-resumable
                self.obs.event("stream_window_overflow",
                               job_id=job.fid,
                               limit=self.stream_replay_bytes)
        row = sc["subs"][sub]
        return self._scatter_send(job, row, {"cmd": "stream-data",
                                             "data": text})

    def _scatter_send(self, job: _FleetJob, row: dict,
                      fwd: dict) -> dict | None:
        """One frame to one sub, queue_full absorbed by waiting (the
        client's ack is the backpressure).  None = the frame was
        delivered — directly, or by a redrive that replayed the sub's
        whole buffer onto a survivor (check ``row["live"]`` to tell)."""
        fwd = dict(fwd)
        fwd["job_id"] = row["mjid"]
        attempts = 0
        while True:
            if not row["live"]:
                return None   # a redrive re-homed this sub mid-retry
            try:
                resp = row["conn"].request(fwd)
            except ServiceError:
                return self._scatter_lost(job, row["member"])
            if resp.get("ok"):
                return None
            if resp.get("error") == protocol.ERR_QUEUE_FULL:
                attempts += 1
                if attempts > 240:   # ~60 s stuck: treat the member
                    #   as pathological and re-partition away from it
                    return self._scatter_lost(job, row["member"])
                ra = resp.get("retry_after_s")
                time.sleep(min(0.25, float(ra))
                           if isinstance(ra, (int, float)) and ra > 0
                           else 0.05)
                continue
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"fleet member {row['member']} rejected a scattered "
                f"frame: {resp.get('detail')}")

    def _scatter_lost(self, job: _FleetJob, name: str
                      ) -> dict | None:
        """A sub's member failed mid-frame: declare it down (which
        re-partitions every scatter job, this one included via the
        re-entrant sc lock), then answer from the outcome."""
        self._member_down(name)
        self._scatter_redrive(job, name)   # no-op if _member_down
        #   already re-homed it; covers a member that was ALREADY
        #   marked dead (broken conn on a stale row)
        with self._lock:
            dead = job.terminal is not None or job.retired
        if dead:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"stream {job.fid} lost fleet member(s) past its "
                "replay window; re-open a stream with --resume and "
                "re-send the records")
        return None

    def _scatter_end(self, job: _FleetJob) -> dict | None:
        """Route the trailing record, then stream-end every live sub.
        Index-based walk: a redrive mid-loop APPENDS replacement subs,
        and they need the stream-end too."""
        sc = job.scatter
        for text in sc["asm"].finish():
            err = self._scatter_record(job, text)
            if err is not None:
                return err
        k = 0
        while k < len(sc["subs"]):
            row = sc["subs"][k]
            k += 1
            if not row["live"]:
                continue
            err = self._scatter_send(job, row, {"cmd": "stream-end"})
            if err is not None:
                return err
        return None

    def _scatter_redrive(self, job: _FleetJob, dead: str) -> None:
        """Re-partition a dead member's sub-streams wholesale onto
        survivors: each dead sub's buffered records replay — in their
        original relative order — into a fresh sub, so the positional
        row<->record mapping survives the failover unchanged."""
        sc = job.scatter
        if not sc["lock"].acquire(timeout=60):
            return    # pathological cross-job lock contention: the
            #   result waiter will land the truthful verdict later
        try:
            with self._lock:
                if job.terminal is not None or job.retired:
                    return
            dead_idx = [k for k, r in enumerate(sc["subs"])
                        if r["live"] and r["member"] == dead]
            if not dead_idx:
                return
            job.failovers += 1
            for k in dead_idx:
                row = sc["subs"][k]
                row["live"] = False
                try:
                    row["conn"].close()
                except Exception:
                    pass
            if sc["texts"] is None:
                self._scatter_abandon(job, dead)
                return
            epoch = readmit_epoch_guard(job.epoch, self.epoch)
            for k in dead_idx:
                order = sc["state"].kill(k)
                if not self._scatter_replace(job, order,
                                             sc["texts"][k], epoch):
                    self._scatter_abandon(job, dead)
                    return
            anchor = next(r["member"] for r in sc["subs"]
                          if r["live"])
            with self._lock:
                job.gen += 1
                job.epoch = epoch
                if job.member == dead:
                    # the ledger slot is keyed to job.member: keep it
                    # pointing at a member that still hosts a sub
                    self.ledger.move(job.client, dead, anchor)
                    job.member = anchor
            self.recovered["stream_replayed"] += 1
            self.metrics["recovered"].inc(how="stream_replayed")
            self.obs.event("scatter_redriven", job_id=job.fid,
                           trace_id=job.trace_id, was=dead,
                           subs=len(dead_idx))
            self._say(f"stream {job.fid}: re-partitioned "
                      f"{len(dead_idx)} sub-stream(s) off dead "
                      f"member {dead}")
        finally:
            sc["lock"].release()

    def _scatter_replace(self, job: _FleetJob, order: list,
                         texts: list, epoch: int) -> bool:
        """One replacement sub for one dead sub: open on a survivor,
        adopt the dead sub's record order, replay its buffer.  Safe to
        try several survivors — a half-fed replacement is cancelled
        and its fragment tag burned, so no path is ever written twice.
        """
        from pwasm_tpu.surveil.partition import rewrite_out_args
        sc = job.scatter
        rem = self._deadline_left_ms(job)
        if rem is not None and rem <= 0:
            return False
        cands = self._members_by_depth()
        # members without a live sub first: spread before stacking
        loaded = {r["member"] for r in sc["subs"] if r["live"]}
        cands.sort(key=lambda m: m.name in loaded)
        for m in cands:
            frag_o = f"{sc['o']}.frag{sc['ntag']:02d}"
            frag_s = f"{sc['s']}.frag{sc['ntag']:02d}" \
                if sc["s"] else None
            sc["ntag"] += 1
            sargs = rewrite_out_args(sc["args"], o=frag_o, s=frag_s)
            row = self._scatter_open_sub(
                m, sargs, sc["cwd"], job.client, job.trace_id, rem,
                job.frame.get("priority"))
            if row is None:
                continue
            row.pop("open", None)
            row["o"], row["s"] = frag_o, frag_s
            k = sc["state"].add_sub()
            sc["state"].adopt(k, order)
            sc["subs"].append(row)
            sc["texts"].append(list(texts))
            with self._lock:
                mm = self.members.get(m.name)
                if mm is not None:
                    mm.jobs_routed += 1
                    mm.dispatched_since_poll += 1
            for text in texts:
                err = self._scatter_send(job, row,
                                         {"cmd": "stream-data",
                                          "data": text})
                if err is not None:
                    return False
                if not row["live"]:
                    return True   # re-redriven wholesale already
            if sc["ended"] and row["live"]:
                if self._scatter_send(job, row,
                                      {"cmd": "stream-end"}) \
                        is not None:
                    return False
            self._journal([(REC_ROUTE_PLACE,
                            {"job_id": job.fid, "member": m.name,
                             "mjid": row["mjid"], "gen": job.gen + 1,
                             "epoch": epoch, "sub": k})])
            return True
        return False

    def _scatter_abandon(self, job: _FleetJob, dead: str) -> None:
        sc = job.scatter
        for row in sc["subs"]:
            if row["live"]:
                self._scatter_cancel_sub(row)
        self.recovered["stream_preempted"] += 1
        self.metrics["recovered"].inc(how="stream_preempted")
        self._cache_terminal(job, JOB_PREEMPTED, 75, (
            f"scattered m2m stream interrupted: fleet member {dead} "
            "died and the stream could not be re-partitioned onto "
            "the survivors; every member's emitted sections are "
            "durable in its section cache — re-open a stream and "
            "re-send the records (cached targets cost no device "
            "work)"))

    def _scatter_job_dict(self, job: _FleetJob, nlive: int,
                          nrec: int) -> dict:
        return {"id": job.fid, "state": "running",
                "detail": f"scattered across {nlive} member(s), "
                          f"{nrec} record(s) assigned",
                "client": job.client, "priority": job.priority,
                "trace_id": job.trace_id, "stream": True,
                "member": job.member,
                "submitted_s": round(job.submitted_s, 3)}

    def _scatter_simple(self, job: _FleetJob, cmd: str) -> dict:
        with self._lock:
            term = job.terminal
        if term is not None:
            if cmd == "cancel":
                return protocol.ok(state=term["job"]["state"],
                                   was="terminal")
            if cmd == "inspect":
                return protocol.ok(job=dict(term["job"]),
                                   trace_id=job.trace_id,
                                   flight=None)
            return protocol.ok(job=dict(term["job"]))
        sc = job.scatter
        with sc["lock"]:
            rows = [r for r in sc["subs"] if r["live"]]
            nrec = sc["state"].nrec
            if cmd == "cancel":
                for row in rows:
                    try:
                        row["conn"].request({"cmd": "cancel",
                                             "job_id": row["mjid"]})
                    except ServiceError:
                        pass
                return protocol.ok(state="cancelling",
                                   was="scatter", subs=len(rows))
        j = self._scatter_job_dict(job, len(rows), nrec)
        if cmd == "inspect":
            return protocol.ok(job=j, trace_id=job.trace_id,
                               flight=None)
        return protocol.ok(job=j)

    def _scatter_result(self, job: _FleetJob, req: dict) -> dict:
        """Wait for every live sub's terminal, then merge: fragments
        spliced in global arrival order (surveil/partition.py), the
        summary re-derived, per-member m2m stats summed — one verdict,
        served router-side like every failover verdict."""
        wait = req.get("wait", True)
        timeout = req.get("timeout")
        deadline = time.monotonic() + float(timeout) \
            if isinstance(timeout, (int, float)) else None
        sc = job.scatter
        while True:
            with self._lock:
                term = job.terminal
            if term is not None:
                self._note_retired(job)
                return dict(term)
            expired = deadline is not None \
                and time.monotonic() >= deadline
            with sc["lock"]:
                ended = sc["ended"]
                rows = [r for r in sc["subs"] if r["live"]]
                gen = job.gen
                nrec = sc["state"].nrec
            if not ended:
                if not wait or expired:
                    return protocol.ok(
                        job=self._scatter_job_dict(job, len(rows),
                                                   nrec),
                        pending=True)
                time.sleep(0.1)
                continue
            results: list = []
            lost = False
            for row in rows:
                with self._lock:
                    m = self.members.get(row["member"])
                    alive = m is not None and m.alive
                if not alive or not row["live"]:
                    if alive:   # row re-homed by a redrive
                        lost = True
                        break
                    self._member_down(row["member"])
                    self._scatter_redrive(job, row["member"])
                    lost = True
                    break
                slice_s = 2.0
                if deadline is not None:
                    slice_s = min(slice_s, max(
                        0.05, deadline - time.monotonic()))
                try:
                    with self._dial(m.target, timeout=60.0) as c:
                        resp = c.result(row["mjid"],
                                        wait=wait and not expired,
                                        timeout=slice_s)
                except ServiceError:
                    self._member_down(row["member"])
                    self._scatter_redrive(job, row["member"])
                    lost = True
                    break
                if not resp.get("ok"):
                    return resp
                jj = resp.get("job") or {}
                if resp.get("pending") \
                        or jj.get("state") not in TERMINAL_STATES:
                    if not wait or expired:
                        return protocol.ok(
                            job=self._scatter_job_dict(
                                job, len(rows), nrec),
                            pending=True)
                    results = []
                    break   # still running: next lap re-waits
                results.append((row, resp))
            if lost:
                continue
            if len(results) != len(rows):
                continue
            with sc["lock"]:
                rows2 = [r for r in sc["subs"] if r["live"]]
                moved = job.gen != gen
            if moved or rows2 != rows:
                # a redrive raced the collection: some verdicts came
                # from the OLD placement generation — recollect
                continue
            self._scatter_finish(job, results)
            continue   # the verdict is now job.terminal — serve it

    def _scatter_finish(self, job: _FleetJob,
                        results: list) -> None:
        from pwasm_tpu.surveil.partition import merge_fragments
        sc = job.scatter
        bad = [(row, resp) for row, resp in results
               if (resp.get("job") or {}).get("state") != JOB_DONE]
        if bad:
            # severity: failed > preempted > cancelled — one sub's
            # loss is the fleet job's loss (fragments are partial)
            rank = {JOB_FAILED: 0, JOB_PREEMPTED: 1,
                    JOB_CANCELLED: 2}
            row, resp = min(bad, key=lambda b: rank.get(
                (b[1].get("job") or {}).get("state"), 3))
            jj = resp.get("job") or {}
            st = jj.get("state") or JOB_FAILED
            rc = resp.get("rc") if isinstance(resp.get("rc"), int) \
                else (75 if st == JOB_PREEMPTED else None)
            self._cache_terminal(
                job, st, rc,
                f"scattered m2m sub-stream on member "
                f"{row['member']} landed {st}: "
                f"{jj.get('detail') or ''}",
                stderr_tail=str(resp.get("stderr_tail") or ""))
            return
        try:
            frags, orders, sumpaths = [], [], []
            for row, _resp in results:
                k = sc["subs"].index(row)
                orders.append(sc["state"].orders[k])
                with open(row["o"], "rb") as f:
                    frags.append(f.read())
                if row["s"]:
                    sumpaths.append(row["s"])
            merged = merge_fragments(frags, orders,
                                     sc["state"].nrec,
                                     summary=sc["s"] is not None)
            report, summ = merged if sc["s"] is not None \
                else (merged, None)
            from pwasm_tpu.utils.fsio import \
                write_durable_bytes
            write_durable_bytes(sc["o"], report)
            if summ is not None:
                write_durable_bytes(sc["s"], summ)
            for row, _resp in results:   # fragments served their
                for p in (row["o"], row["s"]):   # purpose
                    if p:
                        try:
                            os.unlink(p)
                        except OSError:
                            pass
            m2m: dict = {}
            for _row, resp in results:
                sub = (resp.get("stats") or {}).get("m2m") or {}
                for k2, v in sub.items():
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool):
                        continue
                    if k2 == "resident_queries":
                        # every sub scores against the SAME resident
                        # set — max, not sum
                        m2m[k2] = max(m2m.get(k2, 0), v)
                    else:
                        m2m[k2] = m2m.get(k2, 0) + v
            stats = {"m2m": m2m,
                     "scatter": {"subs": len(results),
                                 "records": sc["state"].nrec,
                                 "failovers": job.failovers}}
            if sc["stats_path"]:
                import json
                try:
                    write_durable_bytes(
                        sc["stats_path"],
                        json.dumps(stats, indent=2, sort_keys=True)
                        .encode("ascii") + b"\n")
                except OSError:
                    pass
            self.obs.event("scatter_merged", job_id=job.fid,
                           trace_id=job.trace_id,
                           subs=len(results),
                           records=sc["state"].nrec)
            self._cache_terminal(
                job, JOB_DONE, 0,
                f"fleet-scattered m2m: merged {len(results)} member "
                f"fragment(s), {sc['state'].nrec} target(s), "
                f"byte-identical to one un-scattered run",
                stats=stats)
        except (OSError, ValueError) as e:
            self._cache_terminal(
                job, JOB_FAILED, None,
                f"scatter merge failed: {e} — the per-member "
                "fragments are left in place for inspection")

    def _route_simple(self, job: _FleetJob, cmd: str) -> dict:
        """status / cancel / inspect: one forwarded frame, ids
        rewritten at the edge; a dead member answers from the cached
        failover verdict once one exists."""
        if job.scatter is not None:
            return self._scatter_simple(job, cmd)
        for _attempt in (0, 1):
            with self._lock:
                term = job.terminal
                m = self.members.get(job.member)
                mjid, gen = job.mjid, job.gen
            if term is not None:
                if cmd == "cancel":
                    return protocol.ok(
                        state=term["job"]["state"], was="terminal")
                if cmd == "inspect":
                    return protocol.ok(job=dict(term["job"]),
                                       trace_id=job.trace_id,
                                       flight=None)
                return protocol.ok(job=dict(term["job"]))
            if m is None or not m.alive:
                # same orphan rescue as _route_result: a job the
                # death snapshot missed must still reach a verdict
                # through a status/inspect/cancel poll (idempotent —
                # the per-job latch makes a racing health pass win)
                self._member_down(job.member)
                self._recover_job(job)
                continue
            try:
                with self._dial(m.target, timeout=30.0) as c:
                    resp = c.request({"cmd": cmd, "job_id": mjid})
            except ServiceError:
                self._member_down(job.member)
                self._recover_job(job)
                continue
            j = resp.get("job")
            if isinstance(j, dict) and j.get("state") \
                    in TERMINAL_STATES:
                with self._lock:
                    moved = job.gen != gen
                if moved:
                    # same stale-completion fence as _route_result
                    self.metrics["stale_rejected"].inc()
                    self.obs.event("stale_completion_rejected",
                                   job_id=job.fid, gen=gen,
                                   trace_id=job.trace_id)
                    continue
            return self._rewrite(resp, job)
        # recovery is still in flight (or re-placement raced us):
        # reads answer a soft in-progress state — the client's next
        # poll sees the verdict; a cancel must not pretend it acted
        if cmd == "cancel":
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                f"job {job.fid} is failing over after a member "
                "loss; retry the cancel in a moment")
        return protocol.ok(job={
            "id": job.fid, "state": "running",
            "detail": "member lost; failover in progress",
            "trace_id": job.trace_id, "member": job.member})

    def _route_result(self, job: _FleetJob, req: dict) -> dict:
        if job.scatter is not None:
            return self._scatter_result(job, req)
        wait = req.get("wait", True)
        timeout = req.get("timeout")
        deadline = time.monotonic() + float(timeout) \
            if isinstance(timeout, (int, float)) else None
        t0 = self.obs.tracer.now() \
            if self.obs.tracer is not None else 0.0
        while True:
            with self._lock:
                term = job.terminal
                m = self.members.get(job.member)
                mjid, gen = job.mjid, job.gen
            if term is not None:
                self._note_retired(job)
                if self.obs.tracer is not None:
                    self.obs.tracer.complete(
                        "route_result_wait", t0,
                        trace_id=job.trace_id, job_id=job.fid)
                return dict(term)
            expired = deadline is not None \
                and time.monotonic() >= deadline
            slice_s = 2.0
            if deadline is not None:
                slice_s = min(slice_s, max(
                    0.05, deadline - time.monotonic()))
            if m is None or not m.alive:
                # the member is dead and this job has no verdict yet.
                # Honor the CLIENT's contract first: a no-wait poll or
                # an expired timeout answers pending instead of
                # blocking on the recovery.  Then recover: normally
                # _member_down's failover already owns the job, but
                # one admitted in the gap between the death snapshot
                # and its table insertion would be orphaned forever —
                # _recover_job is idempotent (per-job latch), so
                # calling it here is safe either way.
                if not wait or expired:
                    return protocol.ok(
                        job={"id": job.fid, "state": "running",
                             "detail": "member lost; failover in "
                             "progress", "trace_id": job.trace_id,
                             "member": job.member},
                        pending=True)
                self._recover_job(job, None)
                time.sleep(0.05)
                continue
            try:
                with self._dial(m.target, timeout=60.0) as c:
                    resp = c.result(mjid,
                                    wait=wait and not expired,
                                    timeout=slice_s)
            except ServiceError:
                self._member_down(job.member)
                continue
            if not resp.get("ok"):
                return resp
            if resp.get("pending"):
                with self._lock:
                    moved = job.gen != gen
                if moved or (wait and not expired):
                    continue
                return self._rewrite(resp, job)
            with self._lock:
                moved = job.gen != gen
            if moved:
                # fencing at the router edge: this terminal reply was
                # fetched from the placement generation we snapshotted
                # BEFORE a failover re-placed the job — i.e. a stale
                # (possibly zombie) member's completion.  The newer
                # owner's verdict is the only one that counts.
                self.metrics["stale_rejected"].inc()
                self.obs.event("stale_completion_rejected",
                               job_id=job.fid, gen=gen,
                               trace_id=job.trace_id)
                continue
            self._note_retired(job)
            if self.obs.tracer is not None:
                self.obs.tracer.complete(
                    "route_result_wait", t0, trace_id=job.trace_id,
                    job_id=job.fid, member=job.member)
            return self._rewrite(resp, job)

    def _rewrite(self, resp: dict, job: _FleetJob) -> dict:
        out = dict(resp)
        j = out.get("job")
        if isinstance(j, dict):
            j = dict(j)
            j["id"] = job.fid
            j["member"] = job.member
            if job.failovers:
                j["failovers"] = job.failovers
            out["job"] = j
        return out

    @staticmethod
    def _member_health_entry(mh) -> dict:
        """One member's health dict (from a fresh RPC or its cached
        stats block) folded into the verdict-row shape; anything
        unparseable ranks ``unknown`` (aggregated as degraded —
        unknown must never read as healthy)."""
        if not isinstance(mh, dict):
            return {"verdict": "unknown", "firing": []}
        entry = {
            "verdict": str(mh.get("verdict") or "unknown"),
            "firing": [f.get("rule") for f in
                       (mh.get("firing") or [])
                       if isinstance(f, dict)],
        }
        if mh.get("canary") is not None:
            entry["canary"] = mh["canary"]
        return entry

    def _fleet_health(self, fresh: bool = True) -> dict:
        """The fleet verdict (ISSUE 14): a fresh evaluation of the
        router's own rules (member_up gauges, failover counters,
        ledger saturation) FOLDED with every live member's own
        ``health`` verdict — worst wins, so one failing member makes
        the fleet verdict failing even when the router itself is
        clean.  ``fresh=True`` (the `health` verb — a probe must see
        NOW) asks each live member over a new connection;
        ``fresh=False`` (the `stats` verb, called right after a
        synchronous member poll) folds the health block each member's
        stats reply already carries — zero extra RPCs, so a slow
        member cannot stall every `top` refresh by its timeout.  A
        DEAD member needs no verdict penalty — the router's own
        member_down rule is already firing for it."""
        from pwasm_tpu.obs.slo import worst_verdict
        self._refresh_gauges()
        h = self.slo.evaluate()
        h["router"] = True
        members: dict[str, dict] = {}
        with self._lock:
            rows = [(m.name, m.target, m.alive,
                     (m.stats or {}).get("health"))
                    for m in self.members.values()]
        verdicts = [h["verdict"]]
        for name, target, alive, cached in rows:
            if not alive:
                members[name] = {"verdict": "unreachable",
                                 "firing": []}
                continue
            mh = cached
            if fresh:
                mh = None
                try:
                    with self._dial(target, timeout=3.0) as c:
                        resp = c.request({"cmd": "health"})
                    if resp.get("ok"):
                        mh = resp.get("health")
                except (ServiceError, OSError, ValueError,
                        TypeError, KeyError):
                    pass     # unknown ranks degraded below
            entry = self._member_health_entry(mh)
            verdicts.append(entry["verdict"])
            members[name] = entry
        h["members"] = members
        h["verdict"] = worst_verdict(*verdicts)
        return h

    def _fleet_stats(self) -> dict:
        """The fleet-aggregated svc-stats surface: member counters
        summed, lanes labeled by member, plus the ``fleet`` block the
        fleet-aware ``top`` renders."""
        from pwasm_tpu.service.queue import SERVICE_STATS_VERSION
        jobs_sum: dict = {}
        warm_sum: dict = {}
        streams_sum: dict = {}
        lanes: list[dict] = []
        depth = running = maxc = 0
        breaker = 0
        member_rows = []
        with self._lock:
            members = list(self.members.values())
            live = sum(1 for j in self.jobs.values()
                       if not j.retired and j.terminal is None)
        for m in members:
            st = m.stats or {}
            if m.alive:
                depth += int(st.get("queue_depth") or 0)
                running += int(st.get("running") or 0)
                maxc += int(st.get("max_concurrent") or 0)
                breaker = max(breaker,
                              int(st.get("breaker_state") or 0))
                if isinstance(st.get("jobs"), dict):
                    _sum_numeric(jobs_sum, st["jobs"])
                if isinstance(st.get("warm"), dict):
                    _sum_numeric(warm_sum, st["warm"])
                if isinstance(st.get("streams"), dict):
                    _sum_numeric(streams_sum, st["streams"])
                for row in (st.get("lanes") or []):
                    if isinstance(row, dict):
                        r = dict(row)
                        r["member"] = m.name
                        lanes.append(r)
            member_rows.append({
                "name": m.name, "target": m.target,
                "alive": m.alive,
                "queue_depth": m.queue_depth if m.alive else None,
                "running": m.running if m.alive else None,
                "jobs_routed": m.jobs_routed,
                "journal": m.journal_path,
                "fenced": m.fenced,
                "scaled": m.scaled,
                # gray-failure columns (ISSUE 18) — the fleet-aware
                # `top` renders quarantine state from here
                "quarantined": m.quarantined,
                "lat_ewma_ms": round(m.lat_ewma_ms, 2),
                "depth_ewma": round(m.depth_ewma, 2),
                "quarantines": m.quarantines,
            })
        return {
            "stats_version": SERVICE_STATS_VERSION,
            "protocol_version": protocol.PROTOCOL_VERSION,
            "router": True,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "draining": self._draining,
            "queue_depth": depth,
            "running": running,
            "breaker_state": breaker,
            "max_queue": self.ledger.max_queue,
            "max_concurrent": maxc,
            "jobs": jobs_sum,
            "warm": warm_sum,
            "streams": streams_sum,
            "cache": self.cache.stats_dict()
            if self.cache is not None else {"enabled": False},
            "lanes": lanes,
            "fair_share": {
                "max_queue_per_client": self.ledger.max_queue,
                "max_queue_total": self.ledger.max_total,
                "clients": {(c or "default"): n for c, n in
                            self.ledger.client_depths().items()},
            },
            "fleet": {
                "members": member_rows,
                "alive": sum(1 for m in members if m.alive),
                "failovers": self.failovers,
                "jobs_routed": self.ledger.admitted,
                "jobs_recovered": dict(self.recovered),
                "live_jobs": live,
                "quarantined": sum(1 for m in members
                                   if m.alive and m.quarantined),
            },
            # additive: router HA (ISSUE 16) — WAL, epoch fencing,
            # takeover provenance, and the scaler's own accounting
            "ha": {
                "epoch": self.epoch,
                "takeover": self.takeover,
                "lease_ttl_s": self.lease_ttl_s,
                "stream_replay_bytes": self.stream_replay_bytes,
                "members_fenced": sum(
                    1 for m in members if m.alive and m.fenced),
                "journal": {
                    "path": self.rjournal.path
                    if self.rjournal is not None else None,
                    "records": self.rjournal.records_written
                    if self.rjournal is not None else 0,
                    "broken": self.rjournal.broken
                    if self.rjournal is not None else None,
                },
                "scaler": self.scaler.stats_dict()
                if self.scaler is not None else {"enabled": False},
                # additive: gray-failure defense (ISSUE 18) — the
                # quarantine policy in force and the live brownout
                # shed state (0 = admitting every tier)
                "quarantine": {
                    "x": self.quarantine_x,
                    "probation": self.quarantine_probation,
                    "members": sum(1 for m in members
                                   if m.alive and m.quarantined),
                },
                "shed": {
                    "level": self._shed_level,
                    "priority_lanes": list(self.priority_lanes),
                    "lanes_shed": list(
                        self.priority_lanes[len(self.priority_lanes)
                                            - self._shed_level:])
                    if self._shed_level > 0 else [],
                },
            },
            # additive: the aggregated fleet verdict (ISSUE 14) —
            # the fleet-aware `top`'s alerts pane reads it here.
            # fresh=False: the member poll the stats verb just ran
            # already carries each member's health block — no second
            # RPC round
            "health": self._fleet_health(fresh=False),
        }


def route_main(argv: list[str], stdout=None, stderr=None) -> int:
    """The ``pwasm-tpu route`` entry point."""
    stderr = stderr if stderr is not None else sys.stderr
    opts: dict[str, str] = {}
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            opts[k] = v
        elif a in ("-h", "--help"):
            stderr.write(_ROUTE_USAGE)
            return EXIT_USAGE
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid argument: {a}\n")
            return EXIT_USAGE
    standby_of = opts.pop("standby-of", None)
    backends = [b for b in
                (opts.pop("backends", "") or "").split(",") if b]
    sock = opts.pop("socket", None)
    listen = opts.pop("listen", None)
    if standby_of is not None:
        # a standby's whole identity comes from the primary's journal
        # — a flag-supplied member set or endpoint would let the two
        # disagree about the fleet, which is exactly the split-brain
        # the journal exists to prevent.  Refuse LOUDLY.
        if backends:
            stderr.write(f"{_ROUTE_USAGE}\nError: --standby-of and "
                         "--backends are mutually exclusive — the "
                         "standby inherits the member set from the "
                         "primary's journal (its last `members` "
                         "record), never from flags\n")
            return EXIT_USAGE
        if sock or listen:
            stderr.write(f"{_ROUTE_USAGE}\nError: --standby-of and "
                         "--socket/--listen are mutually exclusive — "
                         "on takeover the standby binds the "
                         "PRIMARY's socket (that is the point)\n")
            return EXIT_USAGE
    elif not backends:
        stderr.write(f"{_ROUTE_USAGE}\nError: --backends=TARGET"
                     "[,TARGET...] is required\n")
        return EXIT_USAGE
    elif not sock and not listen:
        stderr.write(f"{_ROUTE_USAGE}\nError: --socket=PATH and/or "
                     "--listen=HOST:PORT is required\n")
        return EXIT_USAGE
    if listen is not None:
        if not is_tcp_target(listen):
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --listen value: "
                         f"{listen} (HOST:PORT)\n")
            return EXIT_USAGE
    nums: dict[str, int | None] = {}
    for knob, dflt in (("max-queue", 64), ("max-queue-total", None),
                       ("max-results", 4096)):
        val = opts.pop(knob, None)
        if val is None:
            nums[knob] = dflt
        elif val.isascii() and val.isdigit() and int(val) >= 1:
            nums[knob] = int(val)
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --{knob} value: "
                         f"{val}\n")
            return EXIT_USAGE
    poll = 0.5
    val = opts.pop("poll-interval", None)
    if val is not None:
        import math
        try:
            poll = float(val)
            if poll <= 0 or not math.isfinite(poll):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --poll-interval "
                         f"value: {val}\n")
            return EXIT_USAGE
    lease_ttl = DEFAULT_LEASE_TTL_S
    val = opts.pop("lease-ttl", None)
    if val is not None:
        import math
        try:
            lease_ttl = float(val)
            if lease_ttl <= 0 or not math.isfinite(lease_ttl):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --lease-ttl "
                         f"value: {val}\n")
            return EXIT_USAGE
    stream_replay_bytes = 4 << 20
    val = opts.pop("stream-replay-bytes", None)
    if val is not None:
        if val.isascii() and val.isdigit():
            stream_replay_bytes = int(val)
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid "
                         f"--stream-replay-bytes value: {val}\n")
            return EXIT_USAGE
    priority_lanes: tuple[str, ...] | None = None
    val = opts.pop("priority-lanes", None)
    if val is not None:
        from pwasm_tpu.service.daemon import _CLIENT_RE
        lanes = [l.strip() for l in val.split(",")]
        if (not lanes or any(not l or not _CLIENT_RE.match(l)
                             for l in lanes)
                or len(set(lanes)) != len(lanes)):
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --priority-lanes "
                         f"value: {val}\n")
            return EXIT_USAGE
        priority_lanes = tuple(lanes)
    quarantine_x = 4.0
    val = opts.pop("quarantine-x", None)
    if val is not None:
        import math
        try:
            quarantine_x = float(val)
            if quarantine_x < 0 or not math.isfinite(quarantine_x) \
                    or (0 < quarantine_x < 1.0):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --quarantine-x "
                         f"value: {val} (a multiple >= 1, or 0 to "
                         "disable)\n")
            return EXIT_USAGE
    quarantine_probation = 3
    val = opts.pop("quarantine-probation", None)
    if val is not None:
        if val.isascii() and val.isdigit() and int(val) >= 1:
            quarantine_probation = int(val)
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid "
                         f"--quarantine-probation value: {val}\n")
            return EXIT_USAGE
    scale_policy = None
    val = opts.pop("scale-policy", None)
    if val is not None:
        from pwasm_tpu.fleet.scaler import load_scale_policy
        try:
            scale_policy = load_scale_policy(val)
        except ValueError as e:
            stderr.write(f"{_ROUTE_USAGE}\nError: {e}\n")
            return EXIT_USAGE
    journal_dir = opts.pop("journal-dir", None)
    result_cache = opts.pop("result-cache", None)
    if result_cache == "off" or (result_cache is not None
                                 and not result_cache.strip()):
        result_cache = None
    result_cache_max_bytes = None
    val = opts.pop("result-cache-max-bytes", None)
    if val is not None:
        if val.isascii() and val.isdigit() and int(val) >= 1:
            result_cache_max_bytes = int(val)
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid "
                         f"--result-cache-max-bytes value: {val}\n")
            return EXIT_USAGE
    metrics_textfile = opts.pop("metrics-textfile", None)
    log_json = opts.pop("log-json", None)
    trace_json = opts.pop("trace-json", None)
    slo_rules = None
    val = opts.pop("slo-rules", None)
    if val is not None:
        if val == "off":
            slo_rules = "off"
        else:
            from pwasm_tpu.obs.slo import load_rules_file
            try:
                slo_rules = load_rules_file(val)
            except ValueError as e:
                stderr.write(f"{_ROUTE_USAGE}\nError: {e}\n")
                return EXIT_USAGE
    max_frame_bytes = protocol.MAX_FRAME_BYTES
    val = opts.pop("max-frame-bytes", None)
    if val is not None:
        if val.isascii() and val.isdigit() and int(val) >= 1:
            max_frame_bytes = int(val)
        else:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid "
                         f"--max-frame-bytes value: {val}\n")
            return EXIT_USAGE
    tls_cert = opts.pop("tls-cert", None)
    tls_key = opts.pop("tls-key", None)
    tls_client_ca = opts.pop("tls-client-ca", None)
    if (tls_cert is None) != (tls_key is None):
        stderr.write(f"{_ROUTE_USAGE}\nError: --tls-cert and "
                     "--tls-key must be given together\n")
        return EXIT_USAGE
    if tls_client_ca is not None and tls_cert is None:
        stderr.write(f"{_ROUTE_USAGE}\nError: --tls-client-ca "
                     "requires --tls-cert/--tls-key\n")
        return EXIT_USAGE
    tls = None
    if tls_cert is not None:
        from pwasm_tpu.fleet.transport import ServerTLS
        try:
            tls = ServerTLS(tls_cert, tls_key,
                            client_ca=tls_client_ca)
        except ValueError as e:
            stderr.write(f"{_ROUTE_USAGE}\nError: {e}\n")
            return EXIT_USAGE
    member_tls_ca = opts.pop("member-tls-ca", None)
    member_tls_cert = opts.pop("member-tls-cert", None)
    member_tls_key = opts.pop("member-tls-key", None)
    if (member_tls_cert is None) != (member_tls_key is None):
        stderr.write(f"{_ROUTE_USAGE}\nError: --member-tls-cert and "
                     "--member-tls-key must be given together\n")
        return EXIT_USAGE
    if member_tls_cert is not None and member_tls_ca is None:
        stderr.write(f"{_ROUTE_USAGE}\nError: --member-tls-cert/"
                     "--member-tls-key need --member-tls-ca=PEM\n")
        return EXIT_USAGE
    member_tls = None
    if member_tls_ca is not None:
        from pwasm_tpu.fleet.transport import ClientTLS
        try:
            member_tls = ClientTLS(member_tls_ca,
                                   certfile=member_tls_cert,
                                   keyfile=member_tls_key)
        except ValueError as e:
            stderr.write(f"{_ROUTE_USAGE}\nError: {e}\n")
            return EXIT_USAGE
    member_token = opts.pop("member-token", None)
    auth_tokens = opts.pop("auth-tokens", None)
    if auth_tokens is not None and not auth_tokens.strip():
        stderr.write(f"{_ROUTE_USAGE}\nInvalid --auth-tokens "
                     "value: must name a token file\n")
        return EXIT_USAGE
    rate_limit = None
    val = opts.pop("rate-limit", None)
    if val is not None:
        from pwasm_tpu.service.queue import parse_rate_limit
        try:
            rate_limit = parse_rate_limit(val)
        except ValueError as e:
            stderr.write(f"{_ROUTE_USAGE}\nInvalid --rate-limit "
                         f"value: {val} ({e})\n")
            return EXIT_USAGE
    if opts:
        stderr.write(f"{_ROUTE_USAGE}\nInvalid argument: "
                     f"--{next(iter(opts))}\n")
        return EXIT_USAGE
    router_kwargs = dict(
        journal_dir=journal_dir,
        max_queue=nums["max-queue"],
        max_queue_total=nums["max-queue-total"],
        max_results=nums["max-results"],
        poll_interval=poll, stderr=stderr,
        metrics_textfile=metrics_textfile,
        log_json=log_json, trace_json=trace_json,
        slo_rules=slo_rules,
        result_cache=result_cache,
        result_cache_max_bytes=result_cache_max_bytes,
        lease_ttl_s=lease_ttl, scale_policy=scale_policy,
        stream_replay_bytes=stream_replay_bytes,
        priority_lanes=priority_lanes,
        quarantine_x=quarantine_x,
        quarantine_probation=quarantine_probation,
        max_frame_bytes=max_frame_bytes,
        tls=tls, member_tls=member_tls, member_token=member_token,
        auth_tokens=auth_tokens, rate_limit=rate_limit)
    if standby_of is not None:
        from pwasm_tpu.fleet.standby import run_standby
        return run_standby(standby_of, stderr=stderr,
                           router_kwargs=router_kwargs)
    try:
        router = Router(backends, socket_path=sock, listen=listen,
                        **router_kwargs)
    except ValueError as e:
        stderr.write(f"{_ROUTE_USAGE}\nError: {e}\n")
        return EXIT_USAGE
    try:
        return router.serve()
    except PwasmError as e:
        stderr.write(str(e))
        return e.exit_code
