"""Epoch-lease fencing: the one mechanism standing between a
network-partitioned-but-alive fleet member and silent report
corruption (ISSUE 16).

The failure class
-----------------
Fleet failover (docs/FLEET.md) re-admits a dead member's in-flight
jobs as ``--resume`` continuations on a sibling.  Death detection is
evidence-based (consecutive stats-poll failures), so a member that is
merely PARTITIONED from the router looks exactly like a dead one —
and once the sibling's resume starts, two processes are appending to
the same report lineage.  Two resumers of one report is the one
failure class the ckpt-v2 clean-prefix contract cannot absorb: each
side's journal is internally consistent, but the merged history is
garbage.

The fix, in three interlocking pieces
-------------------------------------
1. **Epoch**: the router keeps a monotonic fleet epoch, durably
   journaled.  Every failover re-admission and every router
   restart/takeover bumps it — a bump means "placements made under
   earlier epochs may have been superseded".
2. **Lease**: members accept work only under a router-granted lease
   ``{epoch, ttl_s}``, heartbeated by piggybacking on the existing
   stats poll (no new RPC round-trips).  :class:`EpochLease` is the
   member-side latch: grants with a LOWER epoch than the member has
   already seen are refused (a stale router cannot re-arm a member
   the fleet has moved past).
3. **Self-fence**: a member whose lease TTL expires fences itself —
   it preempts in-flight jobs at the next batch boundary (landing a
   valid durable ckpt, exactly like a drain) and answers new
   ``submit``/``stream``/``stream-data`` frames with the ``fenced``
   error — so by the time the router's strike window declares it dead
   and a sibling resumes, the zombie has already stopped writing.
   The router edge independently rejects stale completions (a
   terminal reply whose placement generation changed mid-request),
   so even a fence that lands LATE cannot publish a superseded
   verdict.

:func:`readmit_epoch_guard` is the choke point the qa gate
(``qa/check_supervision.py`` fencing registry) pins: any code path
that re-admits a started job as ``--resume`` must route its epoch
bookkeeping through this helper, so "resume without fencing" cannot
be reintroduced silently.

Jax-free by construction (enforced by the fleet jax-free gate): this
runs inside the router and the daemon's socket threads.
"""

from __future__ import annotations

import threading
import time

# default lease TTL granted by the router.  Long enough that two
# consecutive 2 s stats polls can be missed without fencing a healthy
# member on a scheduling hiccup; short enough that a real partition
# fences well inside the window a human would need to even notice.
DEFAULT_LEASE_TTL_S = 15.0


class EpochLease:
    """The member-side lease latch (one per daemon, thread-safe).

    Ungoverned until the first grant: a standalone ``serve`` daemon
    that never meets a router keeps today's behaviour exactly — no
    TTL, no fencing, ``expired()`` never fires.  The first
    ``lease-grant`` (or lease-carrying stats poll) latches the member
    into governed mode; from then on the lease must be heartbeated or
    the member self-fences."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.governed = False      # latched by the first grant
        self.epoch = 0             # highest epoch ever seen (monotone)
        self.ttl_s = 0.0
        self.fenced = False
        self.fences = 0            # lifetime fence transitions
        self.fence_reason = ""
        self._deadline = float("inf")

    def grant(self, epoch: int, ttl_s: float) -> tuple[bool, str]:
        """Accept or refuse a lease grant/heartbeat.

        Returns ``(accepted, detail)``.  A grant at an epoch LOWER
        than the member has already seen is refused — that is the
        stale-router signature (the fleet bumped past it during a
        failover or takeover this router never saw).  An accepted
        grant refreshes the TTL deadline and clears any standing
        fence: the router is the epoch source of truth, so a
        heartbeat at the current (or newer) epoch means every resume
        race the fence guarded against has been fenced at the router
        edge already."""
        if not isinstance(epoch, int) or isinstance(epoch, bool) \
                or epoch < 1:
            return False, f"lease epoch must be an integer >= 1, " \
                          f"got {epoch!r}"
        try:
            ttl = float(ttl_s)
        except (TypeError, ValueError):
            return False, f"lease ttl_s must be a number, got {ttl_s!r}"
        if not ttl > 0 or ttl != ttl or ttl == float("inf"):
            return False, f"lease ttl_s must be finite and > 0, " \
                          f"got {ttl_s!r}"
        with self._lock:
            if epoch < self.epoch:
                return False, (
                    f"stale epoch {epoch} < member epoch "
                    f"{self.epoch}: this member has seen a newer "
                    f"fleet epoch; the granting router is behind a "
                    f"failover/takeover and must not re-arm it")
            self.governed = True
            self.epoch = epoch
            self.ttl_s = ttl
            self._deadline = self._clock() + ttl
            if self.fenced:
                self.fenced = False
                self.fence_reason = ""
            return True, ""

    def expired(self) -> bool:
        """True when a governed, not-yet-fenced lease has outlived its
        TTL — the daemon's tick loop turns this into a self-fence."""
        with self._lock:
            return self.governed and not self.fenced \
                and self._clock() > self._deadline

    def fence(self, reason: str) -> bool:
        """Latch the fence.  Returns True on the 0->1 transition (the
        caller preempts jobs / counts the metric exactly once)."""
        with self._lock:
            if not self.governed or self.fenced:
                return False
            self.fenced = True
            self.fences += 1
            self.fence_reason = reason
            return True

    def remaining_s(self) -> float:
        with self._lock:
            if not self.governed:
                return float("inf")
            return self._deadline - self._clock()

    def as_dict(self) -> dict:
        """The ``stats``/``health`` lease block (additive schema)."""
        with self._lock:
            out = {"governed": self.governed, "epoch": self.epoch,
                   "ttl_s": self.ttl_s, "fenced": self.fenced,
                   "fences": self.fences}
            if self.governed:
                rem = self._deadline - self._clock()
                out["remaining_s"] = round(rem, 3) \
                    if rem != float("inf") else None
            if self.fenced:
                out["reason"] = self.fence_reason
            return out


def readmit_epoch_guard(job_epoch: int, fleet_epoch: int) -> int:
    """The fencing choke point for ``--resume`` re-admission.

    Called by every code path that re-admits a started job as a
    ``--resume`` continuation (the qa fencing gate enforces this
    statically).  Takes the epoch the job's CURRENT placement was made
    under and the fleet's current epoch; returns the epoch to stamp
    the NEW placement with.  Raises ``RuntimeError`` if the invariant
    that makes resume safe is broken — a re-admission running under an
    epoch NEWER than the fleet's own would mean two routers disagree
    about who owns the fleet, which is exactly the double-resume race
    fencing exists to prevent.
    """
    if job_epoch > fleet_epoch:
        raise RuntimeError(
            f"fencing violation: job placed under epoch {job_epoch} "
            f"but the fleet epoch is {fleet_epoch} — a re-admission "
            f"would race a newer owner's resume of the same report")
    return fleet_epoch
