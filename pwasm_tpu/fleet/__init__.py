"""Fleet federation: many serve daemons, one submit surface.

``pwasm_tpu/fleet/`` turns N independent serve daemons (PR 5-11) into
one crash-tolerant fleet behind a single endpoint:

- ``transport``  — the TCP transport joining the unix socket: target
  parsing/connecting shared by :class:`~pwasm_tpu.service.client.
  ServiceClient`, ``serve --listen`` and the router;
- ``ledger``     — the global fair-share ledger: per-client fleet-wide
  admission quotas and placement accounting extending each daemon's
  DRR client identities across processes;
- ``router``     — the ``pwasm-tpu route`` daemon: full-protocol
  fan-out over N member daemons with least-queue-depth placement and
  journal-aware failover (a member killed mid-job has its journal read
  and its started-unfinished jobs re-admitted to a sibling as
  ``--resume`` continuations — the PR 9 kill -9 drill, across
  processes).

Like ``service/``, ``obs/`` and ``stream/``, every module here is
jax-free (gated by ``qa/check_supervision.py``
``find_fleet_violations``): the fleet layer moves frames and files,
never tensors.
"""
