"""The service transport layer: unix sockets joined by (optionally
TLS-wrapped) TCP.

Every service surface (``serve``, ``submit``, ``route``, ``top``,
``svc-stats``) names its peer with one *target* string:

- a filesystem path (any string containing ``/``, or anything that is
  not ``host:port`` shaped) is a unix socket — the single-host default,
  with kernel-attested ``SO_PEERCRED`` client identity.  The socket
  inode is created owner-only (0600): the filesystem is the unix
  transport's authentication layer;
- ``HOST:PORT`` (e.g. ``10.0.0.7:9211``, ``localhost:9211``) is TCP —
  the cross-host transport fleet federation runs on.  Plaintext TCP has
  no peer credentials; with ``--tls-cert/--tls-key`` the listener
  upgrades to TLS (stdlib ``ssl``, TLS 1.2 floor) and with
  ``--tls-client-ca`` it demands a client certificate (mTLS), whose
  subject CN becomes a kernel-grade *attested* identity (``cn:<name>``)
  ranking above the free-form ``client_token`` in
  ``protocol.resolve_client_identity``.  The NDJSON protocol itself is
  byte-identical on every transport.

This module is the ONE place sockets are made and wrapped: parsing,
connecting, listening, TLS context construction and the per-connection
server handshake all live here (gated by
``qa/check_supervision.py::find_tls_violations`` — raw ``socket`` /
``ssl`` use anywhere else in ``pwasm_tpu/`` is tier-1-fatal), so the
client, the daemon and the router cannot disagree about what a target
string means or which protocol floor it speaks.

Certificate verification is chain-of-trust against the configured CA
bundle, NOT hostname matching (``check_hostname=False``): fleet
certificates attest *identities* (their CN), and members are dialed by
whatever address the operator listed — pinning the CA is the contract.

Jax-free like the rest of ``pwasm_tpu/fleet/`` (gated by
``qa/check_supervision.py::find_fleet_violations``).
"""

from __future__ import annotations

import os
import re
import socket
import ssl

# HOST:PORT — host is anything path-free and colon-free (DNS name or
# IPv4); a string with "/" can only be a unix path.  IPv6 literals are
# deliberately out of the grammar (brackets would collide with shells);
# use a DNS name.
_TCP_RE = re.compile(r"^(?P<host>[^/:\s]+):(?P<port>\d{1,5})$")

# member names double as journal filenames and metric label values:
# keep the charset boring
_NAME_BAD = re.compile(r"[^A-Za-z0-9_.-]")

# a handshake must finish promptly or the connection thread would be
# parked forever by a client that connected and went silent — the same
# slow-loris shape the idle reaper bounds for established streams
HANDSHAKE_TIMEOUT_S = 10.0


def is_tcp_target(target: str) -> bool:
    """True when ``target`` is ``HOST:PORT`` shaped (a path — anything
    with a ``/`` or no ``:<digits>`` tail — is a unix socket)."""
    return bool(_TCP_RE.match(target or ""))


def split_hostport(target: str) -> tuple[str, int]:
    m = _TCP_RE.match(target or "")
    if not m or not 0 <= int(m.group("port")) <= 65535:
        raise ValueError(
            f"not a HOST:PORT target: {target!r} (port 0-65535)")
    return m.group("host"), int(m.group("port"))


# ---------------------------------------------------------------------------
# TLS configuration (ISSUE 19): built ONCE at startup — a bad cert path
# fails the process before the socket exists, never the first client
# ---------------------------------------------------------------------------
class ServerTLS:
    """Server-side TLS for a TCP listener: ``--tls-cert/--tls-key``
    [+ ``--tls-client-ca`` for mTLS].  Construction validates and
    loads everything eagerly; a broken file is a startup ValueError,
    not a per-connection surprise."""

    def __init__(self, certfile: str, keyfile: str,
                 client_ca: str | None = None,
                 handshake_timeout_s: float = HANDSHAKE_TIMEOUT_S):
        self.certfile = certfile
        self.keyfile = keyfile
        self.client_ca = client_ca
        self.handshake_timeout_s = handshake_timeout_s
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        try:
            ctx.load_cert_chain(certfile, keyfile)
        except (OSError, ssl.SSLError) as e:
            raise ValueError(
                f"cannot load --tls-cert={certfile} / "
                f"--tls-key={keyfile}: {e}")
        if client_ca:
            try:
                ctx.load_verify_locations(client_ca)
            except (OSError, ssl.SSLError) as e:
                raise ValueError(
                    f"cannot load --tls-client-ca={client_ca}: {e}")
            ctx.verify_mode = ssl.CERT_REQUIRED
        self.ctx = ctx
        self.mutual = bool(client_ca)

    def wrap(self, conn: socket.socket) -> ssl.SSLSocket:
        """Run the server-side handshake on an accepted connection,
        bounded by the handshake timeout.  Raises ``OSError`` /
        ``ssl.SSLError`` on any failure (plaintext probe, protocol
        downgrade, mid-handshake disconnect, missing client cert) —
        the caller counts and closes."""
        old = conn.gettimeout()
        conn.settimeout(self.handshake_timeout_s)
        tls = self.ctx.wrap_socket(conn, server_side=True)
        tls.settimeout(old)
        return tls


class ClientTLS:
    """Client-side TLS: ``--tls-ca`` pins the server's issuing CA
    (chain verification, hostnames deliberately unchecked — see the
    module docstring) plus an optional ``--tls-cert/--tls-key`` client
    certificate for mTLS listeners."""

    def __init__(self, ca: str, certfile: str | None = None,
                 keyfile: str | None = None):
        self.ca = ca
        self.certfile = certfile
        self.keyfile = keyfile
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        try:
            ctx.load_verify_locations(ca)
        except (OSError, ssl.SSLError) as e:
            raise ValueError(f"cannot load --tls-ca={ca}: {e}")
        if certfile:
            try:
                ctx.load_cert_chain(certfile, keyfile or certfile)
            except (OSError, ssl.SSLError) as e:
                raise ValueError(
                    f"cannot load client --tls-cert={certfile} / "
                    f"--tls-key={keyfile}: {e}")
        self.ctx = ctx


def server_handshake(conn: socket.socket, tls: "ServerTLS",
                     on_failure=None) -> ssl.SSLSocket | None:
    """The accept-side TLS upgrade: returns the wrapped socket, or
    ``None`` after a failed handshake — counted via ``on_failure`` and
    answered with a LOUD close (the peer sees EOF/RST immediately, a
    plaintext probe never hangs), never an exception into the accept
    path."""
    try:
        return tls.wrap(conn)
    except (OSError, ssl.SSLError, ValueError) as e:
        if on_failure is not None:
            try:
                on_failure(e)
            except Exception:
                pass
        try:
            conn.close()
        except OSError:
            pass
        return None


def peer_common_name(conn) -> str | None:
    """The verified peer certificate's subject CN, or None (plaintext
    connection, or a TLS listener that did not require client certs).
    Only a ``CERT_REQUIRED`` handshake ever yields a non-empty peer
    cert, so a returned name is an *attested* identity."""
    if not isinstance(conn, ssl.SSLSocket):
        return None
    try:
        cert = conn.getpeercert()
    except (OSError, ssl.SSLError, ValueError):
        return None
    for rdn in (cert or {}).get("subject", ()):
        for key, value in rdn:
            if key == "commonName" and value:
                return str(value)
    return None


def connect(target: str, timeout: float | None = None,
            tls: ClientTLS | None = None) -> socket.socket:
    """One connected stream socket to ``target`` (AF_INET for
    ``HOST:PORT``, AF_UNIX otherwise).  A ``tls`` config wraps TCP
    connections (handshake included before returning); unix targets
    ignore it — they already carry kernel peer credentials, so one
    client config serves a mixed unix+TLS fleet.  Raises OSError /
    ssl.SSLError like the bare socket calls would — the caller owns
    the error rendering."""
    if is_tcp_target(target):
        host, port = split_hostport(target)
        s = socket.create_connection((host, port), timeout=timeout)
        if tls is not None:
            try:
                # SNI carries the dialed host; verification is
                # CA-chain only (check_hostname=False, see above)
                return tls.ctx.wrap_socket(s, server_hostname=host)
            except (OSError, ssl.SSLError):
                s.close()
                raise
        return s
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if timeout is not None:
        s.settimeout(timeout)
    try:
        s.connect(target)
    except OSError:
        s.close()
        raise
    return s


def make_tcp_listener(spec: str, backlog: int = 16) -> socket.socket:
    """A bound+listening TCP socket for a ``HOST:PORT`` listen spec
    (port 0 = kernel-assigned; read it back via ``getsockname``).
    ``SO_REUSEADDR`` is set so a restarted daemon rebinds without
    waiting out TIME_WAIT — the crash-recovery path must not stall two
    minutes on its own ghost."""
    host, port = split_hostport(spec)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        s.bind((host, port))
        s.listen(backlog)
    except OSError:
        s.close()
        raise
    return s


def make_unix_listener(path: str, backlog: int = 16) -> socket.socket:
    """A bound+listening unix socket at ``path``, chmod 0600 before
    the first accept — the filesystem is the unix transport's
    authentication layer, so the inode must never be born
    group/world-connectable (ISSUE 19).  A stale socket file is
    unlinked (the caller distinguishes stale from live via
    ``socket_alive`` first); raises OSError like the bare calls."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        if os.path.exists(path):
            os.unlink(path)
        s.bind(path)
        os.chmod(path, 0o600)
        s.listen(backlog)
    except OSError:
        s.close()
        raise
    return s


def socket_alive(path: str) -> bool:
    """True when a live listener answers on the unix socket at
    ``path`` — the stale-vs-live test both ``serve`` and ``route`` run
    before binding over an existing socket file."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(0.5)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


def target_name(target: str) -> str:
    """The sanitized member identity a target maps to — journal
    filenames under a shared ``--journal-dir`` and the ``member=``
    metric label both use it.  Unix sockets name by basename (two
    members sharing a journal dir must use distinct socket basenames —
    docs/FLEET.md), TCP targets by ``host_port``."""
    if is_tcp_target(target):
        host, port = split_hostport(target)
        return _NAME_BAD.sub("_", f"{host}_{port}")
    base = target.rstrip("/").rsplit("/", 1)[-1] or "socket"
    return _NAME_BAD.sub("_", base)


def member_journal_path(target: str,
                        journal_dir: str | None) -> str | None:
    """Where a member serving on ``target`` keeps its job journal —
    the placement-policy contract between ``serve --journal-dir`` and
    ``route --journal-dir`` (both compute it HERE, so the router finds
    exactly the file the member wrote):

    - with a shared ``journal_dir`` (durable network storage):
      ``<dir>/<member-name>.journal`` for any transport;
    - without one (fast local disk): the serve default
      ``<socket>.journal`` — readable by a same-host router for unix
      targets, unreachable for TCP targets (returns None: failover
      degrades to resubmit-with---resume, docs/FLEET.md)."""
    if journal_dir:
        return os.path.join(journal_dir,
                            target_name(target) + ".journal")
    if is_tcp_target(target):
        return None
    return target + ".journal"


def router_journal_path(socket_path: str | None, listen: str | None,
                        journal_dir: str | None) -> str | None:
    """Where a router serving on ``socket_path``/``listen`` keeps its
    write-ahead journal (ISSUE 16) — the contract between the primary
    (``route --socket``) and its warm standby (``route --standby-of``),
    both of which compute it HERE so the standby tails exactly the
    file the primary writes.  Same placement policy as member
    journals:

    - with a shared ``journal_dir``: ``<dir>/router-<name>.journal``
      (the ``router-`` prefix keeps it out of the member-journal
      namespace the failover scan reads);
    - without one: ``<socket>.router.journal`` next to the unix
      socket — readable by a same-host standby;
    - TCP-only routers without a journal dir get None (no durable
      path both sides can agree on): the router runs journal-less,
      today's RAM-only behaviour, and says so at startup."""
    name_src = socket_path or listen
    if journal_dir and name_src:
        return os.path.join(
            journal_dir, "router-" + target_name(name_src) + ".journal")
    if socket_path:
        return socket_path + ".router.journal"
    return None
