"""The service transport layer: unix sockets joined by TCP.

Every service surface (``serve``, ``submit``, ``route``, ``top``,
``svc-stats``) names its peer with one *target* string:

- a filesystem path (any string containing ``/``, or anything that is
  not ``host:port`` shaped) is a unix socket — the single-host default,
  with kernel-attested ``SO_PEERCRED`` client identity;
- ``HOST:PORT`` (e.g. ``10.0.0.7:9211``, ``localhost:9211``) is TCP —
  the cross-host transport fleet federation runs on.  TCP has no peer
  credentials, so the client identity there is the explicit
  ``--client-token`` riding every frame (``tok:<name>`` buckets in the
  DRR fair share) and an untokened connection shares the anonymous
  bucket.  The NDJSON protocol itself is byte-identical on both.

This module is the one place the target grammar lives: parsing,
connecting, listening, and the sanitized *member name* used for
journal/metric identities — so the client, the daemon and the router
cannot disagree about what a target string means.

Jax-free like the rest of ``pwasm_tpu/fleet/`` (gated by
``qa/check_supervision.py::find_fleet_violations``).
"""

from __future__ import annotations

import re
import socket

# HOST:PORT — host is anything path-free and colon-free (DNS name or
# IPv4); a string with "/" can only be a unix path.  IPv6 literals are
# deliberately out of the grammar (brackets would collide with shells);
# use a DNS name.
_TCP_RE = re.compile(r"^(?P<host>[^/:\s]+):(?P<port>\d{1,5})$")

# member names double as journal filenames and metric label values:
# keep the charset boring
_NAME_BAD = re.compile(r"[^A-Za-z0-9_.-]")


def is_tcp_target(target: str) -> bool:
    """True when ``target`` is ``HOST:PORT`` shaped (a path — anything
    with a ``/`` or no ``:<digits>`` tail — is a unix socket)."""
    return bool(_TCP_RE.match(target or ""))


def split_hostport(target: str) -> tuple[str, int]:
    m = _TCP_RE.match(target or "")
    if not m or not 0 <= int(m.group("port")) <= 65535:
        raise ValueError(
            f"not a HOST:PORT target: {target!r} (port 0-65535)")
    return m.group("host"), int(m.group("port"))


def connect(target: str, timeout: float | None = None) -> socket.socket:
    """One connected stream socket to ``target`` (AF_INET for
    ``HOST:PORT``, AF_UNIX otherwise).  Raises OSError like the bare
    socket calls would — the caller owns the error rendering."""
    if is_tcp_target(target):
        host, port = split_hostport(target)
        return socket.create_connection((host, port), timeout=timeout)
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if timeout is not None:
        s.settimeout(timeout)
    try:
        s.connect(target)
    except OSError:
        s.close()
        raise
    return s


def make_tcp_listener(spec: str, backlog: int = 16) -> socket.socket:
    """A bound+listening TCP socket for a ``HOST:PORT`` listen spec
    (port 0 = kernel-assigned; read it back via ``getsockname``).
    ``SO_REUSEADDR`` is set so a restarted daemon rebinds without
    waiting out TIME_WAIT — the crash-recovery path must not stall two
    minutes on its own ghost."""
    host, port = split_hostport(spec)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        s.bind((host, port))
        s.listen(backlog)
    except OSError:
        s.close()
        raise
    return s


def target_name(target: str) -> str:
    """The sanitized member identity a target maps to — journal
    filenames under a shared ``--journal-dir`` and the ``member=``
    metric label both use it.  Unix sockets name by basename (two
    members sharing a journal dir must use distinct socket basenames —
    docs/FLEET.md), TCP targets by ``host_port``."""
    if is_tcp_target(target):
        host, port = split_hostport(target)
        return _NAME_BAD.sub("_", f"{host}_{port}")
    base = target.rstrip("/").rsplit("/", 1)[-1] or "socket"
    return _NAME_BAD.sub("_", base)


def member_journal_path(target: str,
                        journal_dir: str | None) -> str | None:
    """Where a member serving on ``target`` keeps its job journal —
    the placement-policy contract between ``serve --journal-dir`` and
    ``route --journal-dir`` (both compute it HERE, so the router finds
    exactly the file the member wrote):

    - with a shared ``journal_dir`` (durable network storage):
      ``<dir>/<member-name>.journal`` for any transport;
    - without one (fast local disk): the serve default
      ``<socket>.journal`` — readable by a same-host router for unix
      targets, unreachable for TCP targets (returns None: failover
      degrades to resubmit-with---resume, docs/FLEET.md)."""
    import os
    if journal_dir:
        return os.path.join(journal_dir,
                            target_name(target) + ".journal")
    if is_tcp_target(target):
        return None
    return target + ".journal"


def router_journal_path(socket_path: str | None, listen: str | None,
                        journal_dir: str | None) -> str | None:
    """Where a router serving on ``socket_path``/``listen`` keeps its
    write-ahead journal (ISSUE 16) — the contract between the primary
    (``route --socket``) and its warm standby (``route --standby-of``),
    both of which compute it HERE so the standby tails exactly the
    file the primary writes.  Same placement policy as member
    journals:

    - with a shared ``journal_dir``: ``<dir>/router-<name>.journal``
      (the ``router-`` prefix keeps it out of the member-journal
      namespace the failover scan reads);
    - without one: ``<socket>.router.journal`` next to the unix
      socket — readable by a same-host standby;
    - TCP-only routers without a journal dir get None (no durable
      path both sides can agree on): the router runs journal-less,
      today's RAM-only behaviour, and says so at startup."""
    import os
    name_src = socket_path or listen
    if journal_dir and name_src:
        return os.path.join(
            journal_dir, "router-" + target_name(name_src) + ".journal")
    if socket_path:
        return socket_path + ".router.journal"
    return None
