"""SLO-driven member auto-scaling (``route --scale-policy=FILE``).

The SLO engine (ISSUE 14) already KNOWS when the fleet is drowning —
``queue_pressure`` and ``queue_wait_burn`` fire while clients wait,
``ledger_saturation`` fires while admissions approach the backstop —
but until now the verdicts only paged a human.  The scaler closes the
loop: sustained pressure spawns a ``serve`` member (warmed and
compile-cached, so its FIRST job is already fast), sustained calm
drains one back down, and every action is journaled (``REC_SCALE``)
so a restarted or taken-over router knows exactly which members it
owns and readopts them instead of leaking processes.

The policy file is JSON::

    {"min_members": 1, "max_members": 4,
     "cooldown_s": 30, "hysteresis": 2, "scale_down_after_s": 120,
     "rules": ["queue_pressure", "queue_wait_burn",
               "ledger_saturation"],
     "spawn": {"socket_dir": "/srv/pwasm",
               "args": ["--warmup", "--compile-cache-dir=/srv/cc"]}}

- **hysteresis**: a rule must fire on ``hysteresis`` CONSECUTIVE
  health ticks before a spawn — one noisy evaluation is a blip, not
  load;
- **cooldown**: at most one action per ``cooldown_s`` — scaling reacts
  on the minutes scale the SLO windows measure, not per tick (the
  anti-flap half of hysteresis);
- **bounds**: total members stay within ``[min_members,
  max_members]``; the scaler only ever retires members IT spawned
  (flag-supplied members are the operator's, not ours);
- **retirement is a drain**: the member is removed from the router's
  table FIRST (so its planned exit never reads as a death and
  triggers failover), then asked to ``drain`` — it finishes in-flight
  work, preempts its queue to durable checkpoints, and exits with
  the documented preempted code (75).

Jax-free like the rest of ``pwasm_tpu/fleet/`` (gated by
``qa/check_supervision.py::find_fleet_violations``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from pwasm_tpu.core.errors import EXIT_PREEMPTED
from pwasm_tpu.service.client import (ServiceClient, ServiceError,
                                      wait_for_socket)

_DEFAULT_RULES = ("queue_pressure", "queue_wait_burn",
                  "ledger_saturation")


def load_scale_policy(path: str) -> dict:
    """Parse + validate a ``--scale-policy`` file; raises ValueError
    with an operator-readable message on any defect (the router must
    refuse a broken policy at startup, not discover it mid-scale)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read --scale-policy {path}: {e}")
    except ValueError as e:
        raise ValueError(f"--scale-policy {path} is not valid "
                         f"JSON: {e}")
    if not isinstance(raw, dict):
        raise ValueError(f"--scale-policy {path} must be a JSON "
                         "object")

    def intval(key: str, dflt: int, lo: int) -> int:
        v = raw.get(key, dflt)
        if not isinstance(v, int) or isinstance(v, bool) or v < lo:
            raise ValueError(f"--scale-policy {key} must be an "
                             f"integer >= {lo} (got {v!r})")
        return v

    pol = {
        "min_members": intval("min_members", 1, 1),
        "max_members": intval("max_members", 4, 1),
        "cooldown_s": float(raw.get("cooldown_s", 30)),
        "hysteresis": intval("hysteresis", 2, 1),
        "scale_down_after_s": float(raw.get("scale_down_after_s",
                                            120)),
    }
    if pol["max_members"] < pol["min_members"]:
        raise ValueError("--scale-policy max_members must be >= "
                         "min_members")
    if not pol["cooldown_s"] >= 0 or not pol["scale_down_after_s"] >= 0:
        raise ValueError("--scale-policy cooldown_s and "
                         "scale_down_after_s must be >= 0")
    rules = raw.get("rules", list(_DEFAULT_RULES))
    if not isinstance(rules, list) \
            or not all(isinstance(r, str) and r for r in rules) \
            or not rules:
        raise ValueError("--scale-policy rules must be a non-empty "
                         "list of SLO rule names")
    pol["rules"] = rules
    spawn = raw.get("spawn")
    if not isinstance(spawn, dict) \
            or not isinstance(spawn.get("socket_dir"), str) \
            or not spawn["socket_dir"]:
        raise ValueError("--scale-policy needs spawn.socket_dir "
                         "(where scaled members' sockets live)")
    args = spawn.get("args", [])
    if not isinstance(args, list) \
            or not all(isinstance(a, str) for a in args):
        raise ValueError("--scale-policy spawn.args must be a list "
                         "of strings")
    pol["spawn"] = {"socket_dir": spawn["socket_dir"],
                    "args": list(args)}
    return pol


def warm_spawn_args(args) -> list:
    """Spawn-argv policy for scaled members: a member joining a shared
    result-cache dir gets ``--cache-prefetch=64`` appended (warm-spawn
    replication — the hottest entries load BEFORE its socket appears,
    so the capacity the scaler adds is fast for repeat traffic from
    its first job).  An explicit ``--cache-prefetch`` in the policy
    wins; cache-off members are left alone."""
    out = list(args)
    if any(a.startswith("--result-cache=") and not a.endswith("=off")
           for a in out) \
            and not any(a.startswith("--cache-prefetch")
                        for a in out):
        out.append("--cache-prefetch=64")
    return out


class FleetScaler:
    """The router's scaling loop body.  Single-threaded: only the
    router's health loop calls :meth:`tick`, so no locking of its own
    state is needed (member-table mutation goes through the router's
    locked ``_add_member``/``_remove_member``)."""

    def __init__(self, router, policy: dict):
        self.router = router
        self.policy = policy
        self.pressure_ticks = 0      # consecutive firing ticks
        self.calm_since: float | None = None
        self.last_action_s = 0.0     # monotonic; 0 = never
        self.spawned = 0
        self.retired = 0
        self._spawn_seq = 0

    # ---- the loop body -------------------------------------------------
    def tick(self) -> None:
        self._reap_dead()
        firing = self._firing_rules()
        pressure = firing & set(self.policy["rules"])
        now = time.monotonic()
        if pressure:
            self.pressure_ticks += 1
            self.calm_since = None
        else:
            self.pressure_ticks = 0
            if self.calm_since is None:
                self.calm_since = now
        if self.last_action_s \
                and now - self.last_action_s < self.policy["cooldown_s"]:
            return                   # cooling down: observe only
        total, scaled_idle = self._census()
        if pressure and self.pressure_ticks >= \
                self.policy["hysteresis"] \
                and total < self.policy["max_members"]:
            self._spawn(sorted(pressure))
            return
        if not pressure and self.calm_since is not None \
                and now - self.calm_since \
                >= self.policy["scale_down_after_s"] \
                and scaled_idle is not None \
                and total > self.policy["min_members"]:
            self._retire(scaled_idle)

    def _firing_rules(self) -> set:
        """Rule names firing NOW: the router's own engine plus every
        member's cached health block (the member-side queue_pressure /
        queue_wait_burn verdicts are the ones that actually see the
        queues)."""
        r = self.router
        names = {f.get("rule") for f in r.slo.firing()}
        with r._lock:
            blocks = [(m.stats or {}).get("health")
                      for m in r.members.values() if m.alive]
        for mh in blocks:
            if isinstance(mh, dict):
                names |= {f.get("rule") for f in
                          (mh.get("firing") or [])
                          if isinstance(f, dict)}
        names.discard(None)
        return names

    def _census(self):
        """(serving member count, an idle scaler-owned member or
        None).  Quarantined members (gray failure, ISSUE 18) are
        excluded from the count — they take no new placements, so
        for capacity purposes they are missing and sustained
        pressure can spawn a replacement; they are also never the
        idle-retire candidate (retiring the slow member the drill is
        watching would erase the probation-exit evidence — the
        quarantine loop owns its fate)."""
        r = self.router
        with r._lock:
            alive = [m for m in r.members.values()
                     if m.alive and not m.quarantined]
            idle = None
            for m in alive:
                if m.scaled and m.queue_depth == 0 and m.running == 0:
                    idle = m
                    break
        return len(alive), idle

    def _reap_dead(self) -> None:
        """Collect exit codes of retired children (no zombies); a
        child that died WITHOUT being retired stays in the member
        table — the router's normal member-death failover owns it."""
        r = self.router
        with r._lock:
            procs = [(m.name, m.proc) for m in r.members.values()
                     if m.scaled and m.proc is not None]
        for _name, p in procs:
            p.poll()

    # ---- actions -------------------------------------------------------
    def _spawn(self, why: list) -> None:
        r = self.router
        sdir = self.policy["spawn"]["socket_dir"]
        sock = None
        for _ in range(1000):
            self._spawn_seq += 1
            cand = os.path.join(sdir,
                                f"scaled-{self._spawn_seq}.sock")
            if not os.path.exists(cand):
                sock = cand
                break
        if sock is None:
            r._say("scaler: no free socket name under "
                   f"{sdir}; not spawning")
            return
        spawn_args = warm_spawn_args(self.policy["spawn"]["args"])
        argv = [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                f"--socket={sock}"] + spawn_args
        try:
            proc = subprocess.Popen(
                argv, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
        except OSError as e:
            r._say(f"scaler: cannot spawn member ({e})")
            return
        if not wait_for_socket(sock, budget_s=30.0):
            r._say(f"scaler: spawned member on {sock} never came "
                   "up; killing it")
            proc.kill()
            proc.wait()
            return
        m = r._add_member(sock, scaled=True)
        m.proc = proc
        self.spawned += 1
        self.last_action_s = time.monotonic()
        self.pressure_ticks = 0
        from pwasm_tpu.service.journal import REC_SCALE
        r._journal([(REC_SCALE, {"action": "spawn", "target": sock,
                                 "pid": proc.pid, "why": why})])
        r.metrics["scaler_actions"].inc(action="spawn")
        r.obs.event("scaler_spawn", member=m.name, target=sock,
                    pid=proc.pid, why=why)
        r._say(f"scaler: spawned member {m.name} on {sock} "
               f"(pressure: {', '.join(why)})")

    def _retire(self, m) -> None:
        """Drain one scaler-owned idle member out of the fleet.
        Order matters: journal the intent, FORGET the member (so its
        planned exit is never mistaken for a death to fail over),
        then drain it and reap the documented preempted exit code."""
        r = self.router
        from pwasm_tpu.service.journal import REC_SCALE
        r._journal([(REC_SCALE, {"action": "retire",
                                 "target": m.target,
                                 "pid": getattr(m.proc, "pid",
                                                None)})])
        r._remove_member(m.name)
        try:
            # the router's dial factory: a TLS/token-armed fleet
            # retires members with the same credentials it polls with
            with r._dial(m.target, timeout=5.0) as c:
                c.request({"cmd": "drain"})
        except (ServiceError, OSError):
            pass                     # already dying is fine
        rc = None
        if m.proc is not None:
            try:
                rc = m.proc.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                rc = m.proc.wait()
        if rc not in (0, EXIT_PREEMPTED, None):
            r._say(f"scaler: retired member {m.name} exited rc={rc} "
                   f"(expected 0 or {EXIT_PREEMPTED})")
        self.retired += 1
        self.last_action_s = time.monotonic()
        self.calm_since = None
        r.metrics["scaler_actions"].inc(action="retire")
        r.obs.event("scaler_retire", member=m.name, target=m.target,
                    rc=rc)
        r._say(f"scaler: retired idle member {m.name} (rc={rc})")

    def shutdown(self) -> None:
        """Router exit: retire every member we own — scaled members
        must not outlive the router that journals their existence."""
        r = self.router
        with r._lock:
            mine = [m for m in r.members.values() if m.scaled]
        for m in mine:
            self._retire(m)

    def stats_dict(self) -> dict:
        with self.router._lock:
            owned = sum(1 for m in self.router.members.values()
                        if m.scaled)
        return {"enabled": True, "owned": owned,
                "spawned": self.spawned, "retired": self.retired,
                "min_members": self.policy["min_members"],
                "max_members": self.policy["max_members"],
                "pressure_ticks": self.pressure_ticks}
