"""The global fair-share ledger: per-daemon DRR identities, fleet-wide.

Each serve daemon already runs weighted deficit-round-robin over its
own clients (``service/queue.py``) — but with N daemons behind one
router, "fair" has to mean fair across the FLEET: one client identity
gets one fleet-wide admission quota (not N per-member quotas it can
sum by spraying), and the router's placement must not let a heavy
client's backlog on member A starve a light client it happens to
co-place there.

The ledger is the router's accounting half of that contract (the
scheduling half stays in each member's DRR — the router forwards the
resolved client identity on every submit frame, so per-member fairness
keeps working unchanged):

- **fleet quota**: ``admit`` counts live (queued-or-running) jobs per
  client across all members and raises :class:`QueueFull` past
  ``max_queue`` per client (or ``max_total`` overall) — the same
  429-shaped contract as a single daemon, now with one ledger no
  spraying can dodge;
- **placement accounting**: per-client-per-member live counts back
  the aggregated fair-share/metrics surfaces (``fair_share.clients``,
  ``pwasm_fleet_client_jobs``) and let a failover ``move`` a job's
  slot between members without touching the client's quota.  (The
  router's least-loaded placement uses its own per-member
  dispatched-since-last-poll counter, NOT these lifetime counts — a
  long-running routed job the member already reports in its stats
  must not be double-counted.)

Jax-free (``qa/check_supervision.py::find_fleet_violations``).
"""

from __future__ import annotations

import threading

from pwasm_tpu.service.queue import QueueFull


class FleetLedger:
    """Thread-safe fleet-wide per-client admission ledger."""

    def __init__(self, max_queue: int = 64,
                 max_total: int | None = None):
        self.max_queue = max(1, int(max_queue))
        self.max_total = max(self.max_queue, int(max_total)) \
            if max_total is not None else self.max_queue * 8
        self._lock = threading.Lock()
        self._live: dict[str, int] = {}       # client -> live jobs
        self._placed: dict[tuple[str, str], int] = {}  # (client,
        #   member) -> live jobs (the fairness-aware placement view)
        self._member_live: dict[str, int] = {}  # member -> router-
        #   placed live jobs (in-flight dispatch pressure the member's
        #   own queue-depth stat hasn't observed yet)
        self.admitted = 0
        self.rejected = 0

    def admit(self, client: str, member: str) -> None:
        """Count one job for ``client`` placed on ``member``; raises
        :class:`QueueFull` past the fleet quota (the router answers
        the protocol's 429 with it — same dance as a single daemon)."""
        with self._lock:
            if self._live.get(client, 0) >= self.max_queue:
                self.rejected += 1
                raise QueueFull(
                    f"client {client or 'default'!s} at the FLEET "
                    f"queue quota ({self.max_queue})")
            if sum(self._live.values()) >= self.max_total:
                self.rejected += 1
                raise QueueFull(
                    f"fleet at total capacity ({self.max_total})")
            self._live[client] = self._live.get(client, 0) + 1
            key = (client, member)
            self._placed[key] = self._placed.get(key, 0) + 1
            self._member_live[member] = \
                self._member_live.get(member, 0) + 1
            self.admitted += 1

    def restore(self, client: str, member: str) -> None:
        """Journal-replay re-admission (router restart/takeover,
        ISSUE 16): count a job that was already admitted — and acked —
        before the crash WITHOUT re-running the quota gate.  The
        admission promise was made by the previous incarnation; a
        replay that answered queue_full for it would turn crash
        recovery into a broken ack, which is exactly what the WAL
        exists to prevent.  (``admitted`` is not re-counted: the
        lifetime counter survives in spirit, not across processes.)"""
        with self._lock:
            self._live[client] = self._live.get(client, 0) + 1
            key = (client, member)
            self._placed[key] = self._placed.get(key, 0) + 1
            self._member_live[member] = \
                self._member_live.get(member, 0) + 1

    def move(self, client: str, src: str, dst: str) -> None:
        """Re-place one live job (failover: ``src`` died, the job now
        runs on ``dst``) — quota unchanged, placement counts move."""
        with self._lock:
            self._dec_placed(client, src)
            key = (client, dst)
            self._placed[key] = self._placed.get(key, 0) + 1
            self._member_live[dst] = \
                self._member_live.get(dst, 0) + 1

    def retire(self, client: str, member: str) -> None:
        """One job reached a terminal state the client can read."""
        with self._lock:
            n = self._live.get(client, 0) - 1
            if n > 0:
                self._live[client] = n
            else:
                self._live.pop(client, None)
            self._dec_placed(client, member)

    def _dec_placed(self, client: str, member: str) -> None:
        key = (client, member)
        n = self._placed.get(key, 0) - 1
        if n > 0:
            self._placed[key] = n
        else:
            self._placed.pop(key, None)
        n = self._member_live.get(member, 0) - 1
        if n > 0:
            self._member_live[member] = n
        else:
            self._member_live.pop(member, None)

    def client_depths(self) -> dict[str, int]:
        """Live fleet-wide jobs per client (the aggregated
        ``fair_share.clients`` block and the
        ``pwasm_fleet_client_jobs`` gauge source)."""
        with self._lock:
            return dict(self._live)

    def member_pressure(self, member: str) -> int:
        """Router-placed LIVE jobs on ``member`` (accounting/gauge
        surface; placement uses the router's dispatched-since-poll
        counter instead — see the module docstring)."""
        with self._lock:
            return self._member_live.get(member, 0)
