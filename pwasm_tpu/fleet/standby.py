"""The warm-standby router (``pwasm-tpu route --standby-of=TARGET``).

A single router in front of N members is a single point of failure:
kill it and every client's submit surface is gone until an operator
notices.  The standby closes that hole with the cheapest HA shape
that actually works for a unix-socket daemon:

- **warmth**: the standby tails the primary's write-ahead journal
  (``fleet/transport.py::router_journal_path`` — both sides compute
  the path, so they cannot disagree about which file it is) and
  re-folds it whenever it grows, so at takeover time the routed-job
  table is already parsed and the promotion is a bind, not a scan;
- **death detection**: the primary is pinged every poll tick; only
  ``_TAKEOVER_STRIKES`` CONSECUTIVE failed pings (same philosophy as
  the router's own member strikes) promote — one slow ping is a busy
  primary, not a dead one;
- **takeover**: the standby constructs a full :class:`Router` on the
  PRIMARY's socket path with the journal-adopted member set and calls
  ``serve()`` — the router's own stale-socket check (`_socket_alive`)
  unlinks the dead primary's socket and binds, its ``_open_journal``
  replays the shared WAL, and the epoch bump it performs fences any
  zombie primary that is merely stalled: members leased to the old
  epoch refuse its writes the moment the new era heartbeats.

The standby inherits EVERYTHING identity-shaped from the journal —
backends from the last ``members`` record, the socket from
``--standby-of`` itself — and ``route_main`` refuses ``--backends``/
``--socket``/``--listen`` alongside ``--standby-of`` loudly, because a
flag-supplied fleet view is exactly the split-brain the journal
exists to prevent.

Jax-free like the rest of ``pwasm_tpu/fleet/`` (gated by
``qa/check_supervision.py::find_fleet_violations``).
"""

from __future__ import annotations

import os
import sys
import time

from pwasm_tpu.core.errors import EXIT_USAGE
from pwasm_tpu.fleet.transport import (is_tcp_target,
                                       router_journal_path)
from pwasm_tpu.resilience.lifecycle import SignalDrain
from pwasm_tpu.service.client import ServiceClient, ServiceError
from pwasm_tpu.service.journal import JobJournal

# consecutive failed pings before the standby promotes itself.  One
# more strike than the router gives its members: a wrong member
# failover re-admits jobs (recoverable); a wrong TAKEOVER binds a
# second router while the first still lives (the epoch fence catches
# it, but there is no reason to race in the first place).
_TAKEOVER_STRIKES = 3


def run_standby(primary: str, stderr=None,
                router_kwargs: dict | None = None) -> int:
    """Tail ``primary``'s journal until it dies, then take over its
    socket as a full router.  Returns the promoted router's exit code
    (or 0 if drained while still standing by)."""
    stderr = stderr if stderr is not None else sys.stderr
    kwargs = dict(router_kwargs or {})
    kwargs.pop("stderr", None)

    def say(msg: str) -> None:
        print(f"pwasm-route: {msg}", file=stderr)

    if is_tcp_target(primary):
        say("error: --standby-of needs the primary's unix SOCKET "
            "path — a takeover binds that socket, and a TCP "
            "endpoint on another host cannot be bound from here")
        return EXIT_USAGE
    jpath = router_journal_path(primary, None,
                                kwargs.get("journal_dir"))
    poll = max(0.05, float(kwargs.get("poll_interval") or 0.5))
    say(f"standing by for router on {primary} "
        f"(tailing {jpath}, poll every {poll}s)")
    strikes = 0
    seen_alive = False
    warm: dict | None = None
    warm_mtime = -1.0
    drain = SignalDrain(stderr=stderr)
    with drain:
        while not drain.requested:
            try:
                with ServiceClient(primary, timeout=3.0) as c:
                    resp = c.request({"cmd": "ping"})
                if not resp.get("ok"):
                    raise ServiceError(f"ping failed: {resp}")
                strikes = 0
                seen_alive = True
            except (ServiceError, OSError):
                # never promote onto a primary we never saw alive AND
                # whose journal does not exist: nothing to inherit
                # means nothing to serve — keep waiting for it to
                # start (the operator may have launched us first)
                if seen_alive or os.path.exists(jpath):
                    strikes += 1
            # warmth: re-fold the journal whenever it grows, so the
            # takeover path starts from parsed state, not a cold file
            try:
                mtime = os.stat(jpath).st_mtime
            except OSError:
                mtime = -1.0
            if mtime != warm_mtime:
                warm_mtime = mtime
                from pwasm_tpu.fleet.router import fold_route_records
                records = JobJournal(jpath).replay()
                warm = fold_route_records(records) if records \
                    else None
            if strikes >= _TAKEOVER_STRIKES:
                break
            time.sleep(poll)
    if drain.requested:
        say("standby drained before any takeover; primary keeps "
            "serving")
        return 0
    backends = (warm or {}).get("members")
    if not backends:
        say(f"error: primary on {primary} is dead but its journal "
            f"({jpath}) holds no members snapshot to inherit — "
            "cannot take over; restart the primary instead")
        return 1
    say(f"primary on {primary} missed {_TAKEOVER_STRIKES} pings — "
        f"TAKING OVER its socket with {len(backends)} member(s) "
        "from the journal")
    # the promoted router replays the shared WAL itself
    # (_open_journal) and bumps the epoch, fencing any zombie primary
    from pwasm_tpu.core.errors import PwasmError
    from pwasm_tpu.fleet.router import Router
    try:
        router = Router(backends, socket_path=primary,
                        takeover=True, stderr=stderr, **kwargs)
        return router.serve()
    except ValueError as e:
        say(f"error: cannot promote: {e}")
        return 1
    except PwasmError as e:
        stderr.write(str(e))
        return e.exit_code
