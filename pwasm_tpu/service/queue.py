"""Bounded FIFO job queue with admission control + service counters.

The queue is the daemon's *admission control* point: a serving process
that accepts unboundedly is just an OOM with extra steps, so ``submit``
fails fast with :class:`QueueFull` (the protocol's ``queue_full`` —
429-shaped: the caller backs off and retries) once ``max_queue`` jobs
wait, and with :class:`Draining` once a drain began.  FIFO on purpose:
report jobs are peers, and predictable completion order is worth more
to a batch fleet than any priority scheme.

:class:`ServiceStats` is the service-level mirror of the per-job
``RunStats``: admission/outcome counters plus a numeric roll-up of
every finished job's stats JSON — the ``stats`` protocol response is
versioned (``stats_version``) because a service consumer reads it
programmatically, not a human eyeball.
"""

from __future__ import annotations

import io
import threading
import time
from collections import deque
from dataclasses import dataclass, field

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_DONE = "done"
JOB_FAILED = "failed"
JOB_PREEMPTED = "preempted"    # drained mid-run (or before starting):
#                                resumable via --resume
JOB_CANCELLED = "cancelled"

TERMINAL_STATES = (JOB_DONE, JOB_FAILED, JOB_PREEMPTED, JOB_CANCELLED)

SERVICE_STATS_VERSION = 1


class QueueFull(Exception):
    """Admission rejected: the bounded queue is at capacity."""


class Draining(Exception):
    """Admission rejected: the service is draining (no new jobs)."""


@dataclass
class Job:
    """One submitted report job and its whole lifecycle record."""

    id: str
    argv: list
    state: str = JOB_QUEUED
    rc: int | None = None
    detail: str = ""
    cancel_requested: bool = False
    submitted_s: float = field(default_factory=time.time)
    started_s: float | None = None
    finished_s: float | None = None
    accessed_s: float = field(default_factory=time.time)  # last
    #   status/result touch — the LRU clock for --max-results eviction
    stats: dict | None = None          # the job's RunStats JSON
    stats_path: str | None = None
    stats_injected: bool = False       # daemon-owned stats tmp file
    stderr_tail: str = ""
    # per-job drain flag: the daemon's SIGTERM (or a cancel) requests
    # it, and the job's cli.run honors it at the next batch boundary —
    # created at submit time so a drain arriving before the job starts
    # still has a flag to pull
    drain: object = field(default=None, repr=False)
    errbuf: io.StringIO = field(default_factory=io.StringIO, repr=False)
    outbuf: io.StringIO = field(default_factory=io.StringIO, repr=False)
    done: threading.Event = field(default_factory=threading.Event,
                                  repr=False)

    def describe(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "rc": self.rc,
            "detail": self.detail,
            "cancel_requested": self.cancel_requested,
            "submitted_s": round(self.submitted_s, 3),
            "started_s": round(self.started_s, 3)
            if self.started_s else None,
            "finished_s": round(self.finished_s, 3)
            if self.finished_s else None,
        }


class JobQueue:
    """Thread-safe bounded FIFO with a draining latch."""

    def __init__(self, max_queue: int = 16):
        self.max_queue = max(1, int(max_queue))
        self._q: deque[Job] = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def submit(self, job: Job) -> int:
        """Admit ``job``; returns its 0-based queue position.  Raises
        :class:`Draining` / :class:`QueueFull` — admission decisions
        are exceptions, not silent drops, so the protocol layer can
        answer with the right wire code."""
        with self._cond:
            if self._draining:
                raise Draining("service is draining")
            if len(self._q) >= self.max_queue:
                raise QueueFull(
                    f"queue at capacity ({self.max_queue})")
            self._q.append(job)
            pos = len(self._q) - 1
            self._cond.notify()
            return pos

    def take(self, timeout: float | None = None) -> Job | None:
        """Pop the oldest queued job (FIFO); None on timeout or when
        draining emptied the queue."""
        with self._cond:
            if not self._q:
                self._cond.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def remove(self, job: Job) -> bool:
        """Remove a still-queued job (the queued-cancel path)."""
        with self._lock:
            try:
                self._q.remove(job)
                return True
            except ValueError:
                return False

    def drain(self) -> list[Job]:
        """Latch the draining state (every later ``submit`` raises
        :class:`Draining`) and return the jobs that were still queued —
        the daemon marks them preempted-resumable, never starts them."""
        with self._cond:
            self._draining = True
            waiting = list(self._q)
            self._q.clear()
            self._cond.notify_all()
            return waiting


class ServiceStats:
    """Service-level counters + the numeric roll-up of job RunStats."""

    def __init__(self) -> None:
        self.t0 = time.time()
        self.jobs_accepted = 0
        self.jobs_rejected = 0        # queue_full admissions
        self.jobs_rejected_draining = 0
        self.jobs_completed = 0
        self.jobs_failed = 0
        self.jobs_preempted = 0
        self.jobs_cancelled = 0
        self.jobs_evicted = 0         # terminal results dropped by
        #                               --result-ttl-s / --max-results
        self._rollup: dict = {}
        self._lock = threading.Lock()

    def rollup_job(self, stats: dict | None) -> None:
        """Fold one finished job's RunStats JSON into the service
        roll-up (numeric leaves summed, dicts recursed, the schema tag
        and derived rates skipped — summing versions or rates would be
        nonsense)."""
        if not isinstance(stats, dict):
            return
        with self._lock:
            _sum_numeric(self._rollup, stats,
                         skip=("stats_version", "aligned_bases_per_s",
                               "preempted"))

    def as_dict(self, queue_depth: int = 0, running: int = 0,
                draining: bool = False, max_queue: int = 0,
                max_concurrent: int = 0,
                breaker_state: int = 0) -> dict:
        from pwasm_tpu.service.protocol import PROTOCOL_VERSION
        with self._lock:
            rollup = _copy_tree(self._rollup)
        backend = rollup.get("backend", {})
        return {
            "stats_version": SERVICE_STATS_VERSION,
            "protocol_version": PROTOCOL_VERSION,
            "uptime_s": round(time.time() - self.t0, 3),
            "draining": draining,
            # queue_depth / running / breaker_state are SOURCED FROM
            # the daemon's metrics registry (the Prometheus surface):
            # one producer, two renderings, so svc-stats and a scrape
            # cannot disagree (ISSUE 6 satellite)
            "queue_depth": queue_depth,
            "running": running,
            "breaker_state": breaker_state,
            "max_queue": max_queue,
            "max_concurrent": max_concurrent,
            "jobs": {
                "accepted": self.jobs_accepted,
                "rejected": self.jobs_rejected,
                "rejected_draining": self.jobs_rejected_draining,
                "completed": self.jobs_completed,
                "failed": self.jobs_failed,
                "preempted": self.jobs_preempted,
                "cancelled": self.jobs_cancelled,
                "evicted": self.jobs_evicted,
            },
            # the warm-pool promise, observable: probes paid vs probe
            # checks answered from the warm process state
            "warm": {
                "backend_probes": backend.get("probes", 0),
                "backend_warm_hits": backend.get("warm_hits", 0),
            },
            "rollup": rollup,
        }


def _sum_numeric(dst: dict, src: dict, skip: tuple = ()) -> None:
    for k, v in src.items():
        if k in skip:
            continue
        if isinstance(v, dict):
            sub = dst.setdefault(k, {})
            if isinstance(sub, dict):
                _sum_numeric(sub, v, skip)
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            prev = dst.get(k, 0)
            if isinstance(prev, (int, float)) \
                    and not isinstance(prev, bool):
                dst[k] = prev + v


def _copy_tree(d: dict) -> dict:
    return {k: _copy_tree(v) if isinstance(v, dict) else v
            for k, v in d.items()}
