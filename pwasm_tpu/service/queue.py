"""Fair-share job queue with per-client admission control + counters.

The queue is the daemon's *admission control* point: a serving process
that accepts unboundedly is just an OOM with extra steps.  Two things
changed from the PR 5 global FIFO (the "millions of users" gaps
ROADMAP item 5 named):

- **per-client fair share**: jobs are grouped by *client identity*
  (socket-peer uid, or an explicit ``client=`` submit field) and
  dequeued by weighted deficit-round-robin over the clients — a
  500-job submitter and a 1-job submitter both make progress, and
  within one client order stays strict FIFO (predictable completion
  order per submitter is part of the contract).  Optional
  ``--priority-lanes=hi,lo`` adds strict priority *tiers* above the
  round-robin: a higher lane is always served before a lower one,
  with DRR fairness among the clients inside each lane;
- **per-client depth quotas**: ``max_queue`` is the PER-CLIENT queued
  ceiling (the old single global cliff let one heavy submitter eat
  every slot, turning admission control into a denial of service for
  everyone else); :class:`QueueFull` now names the client at quota.
  ``max_total`` (default ``8 * max_queue``) keeps the global
  memory-bound backstop.

``submit`` fails fast with :class:`QueueFull` (the protocol's
``queue_full`` — 429-shaped: the caller backs off and retries) and
with :class:`Draining` once a drain began.

:class:`ServiceStats` is the service-level mirror of the per-job
``RunStats``: admission/outcome counters plus a numeric roll-up of
every finished job's stats JSON — the ``stats`` protocol response is
versioned (``stats_version``) because a service consumer reads it
programmatically, not a human eyeball.
"""

from __future__ import annotations

import io
import threading
import time
from collections import deque
from dataclasses import dataclass, field

JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_DONE = "done"
JOB_FAILED = "failed"
JOB_PREEMPTED = "preempted"    # drained mid-run (or before starting):
#                                resumable via --resume
JOB_CANCELLED = "cancelled"

TERMINAL_STATES = (JOB_DONE, JOB_FAILED, JOB_PREEMPTED, JOB_CANCELLED)

SERVICE_STATS_VERSION = 1


class QueueFull(Exception):
    """Admission rejected: the bounded queue is at capacity."""


class Draining(Exception):
    """Admission rejected: the service is draining (no new jobs)."""


@dataclass
class Job:
    """One submitted report job and its whole lifecycle record."""

    id: str
    argv: list
    state: str = JOB_QUEUED
    rc: int | None = None
    detail: str = ""
    cancel_requested: bool = False
    client: str = ""                   # fair-share identity (peer uid
    #   or the submit frame's client= field); "" = anonymous bucket
    priority: str = ""                 # priority lane ("" = default)
    trace_id: str = ""                 # cross-process trace identity
    #   (ISSUE 11): minted by the submitting ServiceClient (or the
    #   daemon when the frame carried none), stamped into the journal,
    #   event-log lines, both sides' Chrome traces, and the flight
    #   record — one greppable id for a job's whole life
    flight: object = field(default=None, repr=False)  # the job's
    #   obs.flight.FlightRecorder (phase walls + event ring), served
    #   by the `inspect` verb and spooled with the result
    prefer_lane: int | None = None     # device-lane affinity hint (a
    #   journal-recovered job asks for the lane it ran on; a stream
    #   job asks for the lane its client's last stream warmed)
    stream: bool = False               # socket-streamed job: input
    #   arrives as stream-data frames, not a file (docs/STREAMING.md)
    feed: object = field(default=None, repr=False)  # the job's
    #   StreamFeed (stream.pafstream) when stream is True
    recovered: bool = False            # re-admitted by journal replay
    seq: int = 0                       # global admission order (drain
    #   and journal replay preserve it across the per-client deques)
    spool: dict | None = None          # disk-spooled result index
    #   ({path, bytes}): the RAM-resident stats/stderr_tail moved to
    #   the spool dir — see daemon._spool_result
    cache: object = field(default=None, repr=False)  # (key,
    #   classified) for a cacheable job that MISSED at admission —
    #   the finished outputs insert under it (service/cache.py)
    delta: tuple | None = field(default=None, repr=False)  # (records
    #   served, records total) when admission re-armed this job as a
    #   --resume over a cached same-family input prefix (ISSUE 17):
    #   finish notes the fractional hit and stamps the job's stats
    #   with the truthful cache_delta counts
    dstate: dict | None = field(default=None, repr=False)  # stream
    #   delta state (ROADMAP 4c): while "holding", stream-data frames
    #   are classified against the cache's per-line digest column
    #   BEFORE the job enters the queue (a re-opened stream delta-hits
    #   like a file input); once "resolved" the daemon keeps mirroring
    #   the server-authoritative digest column so a cleanly finished
    #   stream inserts a delta-indexed entry of its own
    deadline_ms: int | None = None     # REMAINING end-to-end budget
    #   (integer ms) as of admission, from the submit frame's
    #   deadline_ms (ISSUE 18).  None = no deadline: behavior is
    #   byte-identical to before the field existed.  The worker
    #   subtracts the monotonic time since submitted_mono (queue +
    #   lease wait) before exec; a spent budget lands terminal
    #   deadline_exceeded without running.
    submitted_s: float = field(default_factory=time.time)
    submitted_mono: float = field(default_factory=time.monotonic)
    #   monotonic twin of submitted_s: queue-wait and deadline
    #   arithmetic use THIS (a wall-clock step must never fake a
    #   deadline expiry or an EWMA spike —
    #   qa/check_supervision.py::find_clock_violations)
    started_s: float | None = None
    finished_s: float | None = None
    accessed_s: float = field(default_factory=time.time)  # last
    #   status/result touch — the LRU clock for --max-results eviction
    stats: dict | None = None          # the job's RunStats JSON
    stats_path: str | None = None
    stats_injected: bool = False       # daemon-owned stats tmp file
    stderr_tail: str = ""
    # per-job drain flag: the daemon's SIGTERM (or a cancel) requests
    # it, and the job's cli.run honors it at the next batch boundary —
    # created at submit time so a drain arriving before the job starts
    # still has a flag to pull
    drain: object = field(default=None, repr=False)
    errbuf: io.StringIO = field(default_factory=io.StringIO, repr=False)
    outbuf: io.StringIO = field(default_factory=io.StringIO, repr=False)
    done: threading.Event = field(default_factory=threading.Event,
                                  repr=False)

    def describe(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "rc": self.rc,
            "detail": self.detail,
            "cancel_requested": self.cancel_requested,
            "client": self.client,
            "priority": self.priority,
            "trace_id": self.trace_id,
            "stream": self.stream,
            "recovered": self.recovered,
            "submitted_s": round(self.submitted_s, 3),
            "started_s": round(self.started_s, 3)
            if self.started_s else None,
            "finished_s": round(self.finished_s, 3)
            if self.finished_s else None,
        }


class _LaneSched:
    """Weighted deficit-round-robin state for ONE priority lane: a
    strict-FIFO deque per client, a client rotation, and per-client
    deficit counters.  Unit job cost, so with equal weights DRR
    degenerates to plain round-robin over clients — the property the
    fair-share acceptance gate tests (a 1-job submitter never waits
    behind a 500-job submitter's whole backlog)."""

    __slots__ = ("clients", "rr", "deficit")

    def __init__(self) -> None:
        self.clients: dict[str, deque[Job]] = {}
        self.rr: deque[str] = deque()      # client service rotation
        self.deficit: dict[str, float] = {}

    def push(self, job: Job) -> None:
        q = self.clients.get(job.client)
        if q is None:
            q = self.clients[job.client] = deque()
            self.rr.append(job.client)
            self.deficit[job.client] = 0.0
        q.append(job)

    def empty(self) -> bool:
        return not any(self.clients.values())

    def _drop_if_empty(self, client: str) -> None:
        if client in self.clients and not self.clients[client]:
            del self.clients[client]
            del self.deficit[client]
            try:
                self.rr.remove(client)
            except ValueError:
                pass

    def pop(self, weight_of) -> Job | None:
        """One DRR dequeue: the head-of-rotation client is credited
        its weight ONCE per visit (only when its deficit no longer
        covers a job — the mid-burst guard), then serves its OLDEST
        job per unit of deficit; the rotation advances when the burst
        is paid out, so a weight-2 client yields two jobs per rotation
        to a weight-1 client's one.  Weights are clamped positive, so
        deficits grow every full rotation and the loop always
        terminates on a non-empty lane; the credit guard also caps any
        deficit at ``1 + weight`` (no unbounded credit hoarding)."""
        while self.rr:
            c = self.rr[0]
            q = self.clients.get(c)
            if not q:
                self._drop_if_empty(c)
                continue
            if self.deficit[c] < 1.0:    # a fresh visit, not mid-burst
                self.deficit[c] += max(0.05, float(weight_of(c)))
            if self.deficit[c] >= 1.0:
                job = q.popleft()
                self.deficit[c] -= 1.0
                if self.deficit[c] < 1.0 or not q:
                    self.rr.rotate(-1)   # burst paid out: next take
                    #                      serves the NEXT client
                self._drop_if_empty(c)
                return job
            self.rr.rotate(-1)
        return None


class JobQueue:
    """Thread-safe fair-share queue: per-client quotas at admission,
    weighted deficit-round-robin over clients at dequeue, optional
    strict priority lanes above both, and a draining latch.

    ``max_queue`` is the PER-CLIENT depth quota (the global cliff it
    replaces let one heavy submitter starve everyone — see the module
    docstring); ``max_total`` (default ``8 * max_queue``) bounds the
    whole queue.  A single-client workload behaves exactly like the
    old bounded FIFO: same quota arithmetic, same FIFO order."""

    def __init__(self, max_queue: int = 16,
                 max_total: int | None = None,
                 priority_lanes: tuple[str, ...] | None = None):
        self.max_queue = max(1, int(max_queue))
        self.max_total = max(self.max_queue, int(max_total)) \
            if max_total is not None else self.max_queue * 8
        # priority tiers, highest first; () / None = one anonymous lane
        self.priority_lanes = tuple(priority_lanes) \
            if priority_lanes else ("",)
        self._sched = {lane: _LaneSched()
                       for lane in self.priority_lanes}
        self._count = 0
        self._client_counts: dict[str, int] = {}
        self._weights: dict[str, float] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        with self._lock:
            return self._count

    def client_depths(self) -> dict[str, int]:
        """Queued-job count per client (the
        ``pwasm_service_client_queue_depth`` gauge source)."""
        with self._lock:
            return dict(self._client_counts)

    def set_client_weight(self, client: str, weight: float) -> None:
        """Set a client's DRR weight (default 1.0): a weight-2 client
        is served two jobs per rotation for every one a weight-1
        client gets.  Clamped positive."""
        with self._lock:
            self._weights[client] = max(0.05, float(weight))

    def _weight_of(self, client: str) -> float:
        return self._weights.get(client, 1.0)

    def submit(self, job: Job) -> int:
        """Admit ``job``; returns the number of jobs queued ahead of
        it.  Raises :class:`Draining` / :class:`QueueFull` — admission
        decisions are exceptions, not silent drops, so the protocol
        layer can answer with the right wire code.  ``job.priority``
        must be one of the configured lanes (the daemon validates the
        submit field before it gets here)."""
        lane = job.priority or self.priority_lanes[-1]
        with self._cond:
            if self._draining:
                raise Draining("service is draining")
            if lane not in self._sched:
                raise QueueFull(f"unknown priority lane {lane!r}")
            if self._client_counts.get(job.client, 0) \
                    >= self.max_queue:
                raise QueueFull(
                    f"client {job.client or 'default'!s} at queue "
                    f"quota ({self.max_queue})")
            if self._count >= self.max_total:
                raise QueueFull(
                    f"queue at total capacity ({self.max_total})")
            pos = self._count
            job.seq = self._seq
            self._seq += 1
            self._sched[lane].push(job)
            self._count += 1
            self._client_counts[job.client] = \
                self._client_counts.get(job.client, 0) + 1
            self._cond.notify()
            return pos

    def _pop_locked(self) -> Job | None:
        for lane in self.priority_lanes:   # strict tiers, high first
            job = self._sched[lane].pop(self._weight_of)
            if job is not None:
                self._count -= 1
                self._uncount_client(job.client)
                return job
        return None

    def _uncount_client(self, client: str) -> None:
        n = self._client_counts.get(client, 0) - 1
        if n > 0:
            self._client_counts[client] = n
        else:
            self._client_counts.pop(client, None)

    def take(self, timeout: float | None = None) -> Job | None:
        """Dequeue the next job by priority tier then client fair
        share (FIFO within a client); None on timeout or when draining
        emptied the queue."""
        with self._cond:
            if not self._count:
                self._cond.wait(timeout)
            if not self._count:
                return None
            return self._pop_locked()

    def remove(self, job: Job) -> bool:
        """Remove a still-queued job (the queued-cancel path)."""
        with self._lock:
            lane = job.priority or self.priority_lanes[-1]
            sched = self._sched.get(lane)
            if sched is None:
                return False
            q = sched.clients.get(job.client)
            if not q:
                return False
            try:
                q.remove(job)
            except ValueError:
                return False
            sched._drop_if_empty(job.client)
            self._count -= 1
            self._uncount_client(job.client)
            return True

    def drain(self) -> list[Job]:
        """Latch the draining state (every later ``submit`` raises
        :class:`Draining`) and return the jobs that were still queued
        in ADMISSION order — the daemon marks them preempted-
        resumable, never starts them."""
        with self._cond:
            self._draining = True
            return self._empty_locked()

    def preempt_all(self) -> list[Job]:
        """Empty the queue WITHOUT latching the draining state — the
        epoch-fence path (ISSUE 16, ``fleet/fencing.py``): queued jobs
        are preempted now, but admission re-opens the moment a lease
        grant un-fences the member.  A fence is a pause; a drain is an
        exit."""
        with self._cond:
            return self._empty_locked()

    def _empty_locked(self) -> list[Job]:
        waiting: list[Job] = []
        for sched in self._sched.values():
            for q in sched.clients.values():
                waiting.extend(q)
            sched.clients.clear()
            sched.rr.clear()
            sched.deficit.clear()
        waiting.sort(key=lambda j: j.seq)
        self._count = 0
        self._client_counts.clear()
        self._cond.notify_all()
        return waiting


class StreamBook:
    """Per-stream admission quotas + fair-share buffer arbitration
    (ISSUE 10).

    A stream job's records live in its :class:`~pwasm_tpu.stream.
    pafstream.StreamFeed` buffer between the ``stream-data`` frame
    that carried them and the worker that drains them.  Unbounded,
    that buffer is the same OOM-with-extra-steps the job queue's
    admission control exists to prevent — so every feed is gated here
    BEFORE the chunk is committed:

    - **per-stream quota** (``max_buffer`` records, the ``serve
      --stream-buffer`` knob): one stream whose producer outruns its
      consumer answers ``queue_full`` (the protocol's 429 — the client
      backs off on ``retry_backoff_s`` and resends the same frame);
    - **fair share under the global ceiling** (``max_total``, default
      ``4 x max_buffer``): once the streams TOGETHER hit the ceiling,
      a feed is admitted only while that stream sits at or under its
      equal credit share (``max_total / active_streams`` — unit-cost
      DRR degenerates to exactly this equal rotation, the same
      property :class:`_LaneSched` documents).  A heavy stream at the
      ceiling gets backpressure while a light one under its share
      keeps feeding: heavy cannot starve light, the fair-share
      acceptance leg.

    Scheduling BETWEEN stream jobs (which one a worker picks up) rides
    the existing weighted-DRR-over-clients dequeue above — streams are
    ordinary jobs to the queue.  Checks are all-or-nothing per frame,
    so a rejected frame is resendable verbatim."""

    def __init__(self, max_buffer: int = 512,
                 max_total: int | None = None):
        self.max_buffer = max(1, int(max_buffer))
        self.max_total = max(self.max_buffer, int(max_total)) \
            if max_total is not None else self.max_buffer * 4
        self._streams: dict[str, tuple[str, object]] = {}
        self._clients_seen: set[str] = set()   # label universe for the
        #   lag gauge: a finished stream's client reads 0, not gone
        self._done = {"records_in": 0, "records_out": 0, "batches": 0}
        #   retired streams' flow counters — svc-stats totals stay
        #   cumulative after a stream finishes
        self._lock = threading.Lock()

    def register(self, job_id: str, client: str, feed) -> None:
        with self._lock:
            self._streams[job_id] = (client, feed)
            self._clients_seen.add(client)

    def unregister(self, job_id: str) -> None:
        with self._lock:
            row = self._streams.pop(job_id, None)
            if row is not None:
                feed = row[1]
                self._done["records_in"] += feed.records_in
                self._done["records_out"] += feed.records_out
                self._done["batches"] += feed.batches

    def active(self) -> int:
        with self._lock:
            return len(self._streams)

    def admit(self, job_id: str, n: int) -> None:
        """Gate ``n`` more records into ``job_id``'s buffer; raises
        :class:`QueueFull` (quota or fair-share — the message names
        which) instead of admitting.  Unknown streams admit freely:
        the daemon validates the job before calling here.

        A stream whose buffer is EMPTY always admits, even a frame
        larger than the whole quota: the protocol's backoff contract
        is "resend the same frame", so a frame that could never fit
        would livelock the retry dance on an otherwise idle daemon.
        Progress beats strictness — the overage is bounded by one
        already-received frame per stream (the frame ceiling bounds
        its size), and the very next frame backpressures until the
        job drains the buffer back under quota."""
        with self._lock:
            row = self._streams.get(job_id)
            if row is None:
                return
            _client, feed = row
            buffered = feed.buffered
            if not buffered:
                return
            if buffered + n > self.max_buffer:
                raise QueueFull(
                    f"stream {job_id} at its buffer quota "
                    f"({self.max_buffer} records)")
            total = sum(f.buffered
                        for _c, f in self._streams.values())
            if total + n > self.max_total:
                share = max(1, self.max_total
                            // max(1, len(self._streams)))
                if buffered + n > share:
                    raise QueueFull(
                        f"streams at the global buffer ceiling "
                        f"({self.max_total} records); stream "
                        f"{job_id} is over its fair share ({share})")

    def totals(self) -> dict:
        """The roll-up the ``svc-stats`` ``streams`` block reports:
        ``active``/``buffered`` are live, the flow counters are
        cumulative over the daemon's whole life (live + retired)."""
        with self._lock:
            feeds = [f for _c, f in self._streams.values()]
            return {
                "active": len(feeds),
                "buffered": sum(f.buffered for f in feeds),
                "records_in": self._done["records_in"]
                + sum(f.records_in for f in feeds),
                "records_out": self._done["records_out"]
                + sum(f.records_out for f in feeds),
                "batches": self._done["batches"]
                + sum(f.batches for f in feeds),
            }

    def client_lag(self) -> dict[str, int]:
        """Buffered (fed-but-unconsumed) records per client — the
        ``pwasm_stream_lag_records`` gauge source.  Every client that
        ever streamed keeps a series at 0 (a vanished series reads as
        a scrape gap, not an emptied buffer)."""
        with self._lock:
            out = {c: 0 for c in self._clients_seen}
            for client, feed in self._streams.values():
                out[client] = out.get(client, 0) + feed.buffered
            return out

    def client_lag_age(self) -> dict[str, float]:
        """Age of the oldest unconsumed record per client (worst
        stream wins) — the ``pwasm_stream_lag_age_seconds`` gauge
        source; same every-client-keeps-a-series rule as
        :meth:`client_lag`."""
        with self._lock:
            streams = list(self._streams.values())
            out = {c: 0.0 for c in self._clients_seen}
        for client, feed in streams:
            age = feed.lag_age_s() if hasattr(feed, "lag_age_s") \
                else 0.0
            out[client] = max(out.get(client, 0.0), age)
        return out


class ServiceStats:
    """Service-level counters + the numeric roll-up of job RunStats."""

    def __init__(self) -> None:
        self.t0 = time.time()
        self.t0_mono = time.monotonic()   # uptime arithmetic uses the
        #   monotonic twin: an NTP step must not fake (or hide) uptime
        self.jobs_accepted = 0
        self.jobs_rejected = 0        # queue_full admissions
        self.jobs_rejected_draining = 0
        self.jobs_completed = 0
        self.jobs_failed = 0
        self.jobs_preempted = 0
        self.jobs_deadline_exceeded = 0   # subset of preempted whose
        #                                   drain reason was a spent
        #                                   --deadline-s budget
        self.jobs_cancelled = 0
        self.jobs_evicted = 0         # terminal results dropped by
        #                               --result-ttl-s / --max-results
        self.jobs_recovered = 0       # re-admitted by journal replay
        #                               after a daemon crash
        self.journal_replays = 0      # startup replays performed
        self._rollup: dict = {}
        self._lock = threading.Lock()

    def rollup_job(self, stats: dict | None) -> None:
        """Fold one finished job's RunStats JSON into the service
        roll-up (numeric leaves summed, dicts recursed, the schema tag
        and derived rates skipped — summing versions or rates would be
        nonsense)."""
        if not isinstance(stats, dict):
            return
        with self._lock:
            _sum_numeric(self._rollup, stats,
                         skip=("stats_version", "aligned_bases_per_s",
                               "preempted"))

    def as_dict(self, queue_depth: int = 0, running: int = 0,
                draining: bool = False, max_queue: int = 0,
                max_concurrent: int = 0,
                breaker_state: int = 0) -> dict:
        from pwasm_tpu.service.protocol import PROTOCOL_VERSION
        with self._lock:
            rollup = _copy_tree(self._rollup)
        backend = rollup.get("backend", {})
        return {
            "stats_version": SERVICE_STATS_VERSION,
            "protocol_version": PROTOCOL_VERSION,
            "uptime_s": round(time.monotonic() - self.t0_mono, 3),
            "draining": draining,
            # queue_depth / running / breaker_state are SOURCED FROM
            # the daemon's metrics registry (the Prometheus surface):
            # one producer, two renderings, so svc-stats and a scrape
            # cannot disagree (ISSUE 6 satellite)
            "queue_depth": queue_depth,
            "running": running,
            "breaker_state": breaker_state,
            "max_queue": max_queue,
            "max_concurrent": max_concurrent,
            "jobs": {
                "accepted": self.jobs_accepted,
                "rejected": self.jobs_rejected,
                "rejected_draining": self.jobs_rejected_draining,
                "completed": self.jobs_completed,
                "failed": self.jobs_failed,
                "preempted": self.jobs_preempted,
                "deadline_exceeded": self.jobs_deadline_exceeded,
                "cancelled": self.jobs_cancelled,
                "evicted": self.jobs_evicted,
                "recovered": self.jobs_recovered,
            },
            # the warm-pool promise, observable: probes paid vs probe
            # checks answered from the warm process state
            "warm": {
                "backend_probes": backend.get("probes", 0),
                "backend_warm_hits": backend.get("warm_hits", 0),
            },
            "rollup": rollup,
        }


def _sum_numeric(dst: dict, src: dict, skip: tuple = ()) -> None:
    for k, v in src.items():
        if k in skip:
            continue
        if isinstance(v, dict):
            sub = dst.setdefault(k, {})
            if isinstance(sub, dict):
                _sum_numeric(sub, v, skip)
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            prev = dst.get(k, 0)
            if isinstance(prev, (int, float)) \
                    and not isinstance(prev, bool):
                dst[k] = prev + v


def _copy_tree(d: dict) -> dict:
    return {k: _copy_tree(v) if isinstance(v, dict) else v
            for k, v in d.items()}


def parse_rate_limit(spec: str) -> tuple[float, float]:
    """Parse ``--rate-limit=N[/s][:burst]`` → ``(rate_per_s, burst)``.

    ``N`` is requests per second (float, > 0); ``burst`` is the bucket
    depth (>= 1, default ``max(1, rate)`` so a limit below 1/s still
    admits single requests).  Raises ValueError on anything else — the
    CLI turns that into the usual usage error."""
    s = spec.strip()
    burst_s = None
    if ":" in s:
        s, burst_s = s.split(":", 1)
    if s.endswith("/s"):
        s = s[:-2]
    try:
        rate = float(s)
    except ValueError:
        raise ValueError(f"rate-limit rate {s!r} is not a number")
    if not (rate > 0) or rate != rate or rate == float("inf"):
        raise ValueError("rate-limit rate must be a finite number > 0")
    if burst_s is None:
        burst = max(1.0, rate)
    else:
        try:
            burst = float(burst_s)
        except ValueError:
            raise ValueError(
                f"rate-limit burst {burst_s!r} is not a number")
        if not (burst >= 1) or burst == float("inf"):
            raise ValueError("rate-limit burst must be finite and >= 1")
    return rate, burst


class RateLimiter:
    """Per-identity token bucket in front of admission (ISSUE 19).

    One bucket per *resolved* client identity (the same string DRR
    fair-share uses), refilled continuously at ``rate_per_s`` up to
    ``burst``.  A refusal is truthful like brownout shedding: it
    reports the ``retry_after_s`` at which the bucket will actually
    hold a whole token, so a well-behaved client that honors it is
    admitted on its next try.

    Monotonic clock only (the clock-discipline gate bans wall-clock
    deltas); the table is bounded at ``max_clients`` — at the cap,
    full (idle) buckets are swept first since they carry no state an
    attacker could launder by eviction, then oldest-inserted."""

    def __init__(self, rate_per_s: float, burst: float,
                 max_clients: int = 4096):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self.max_clients = max_clients
        # identity -> [tokens, last_refill_mono]
        self._buckets: dict[str, list] = {}
        self._lock = threading.Lock()
        self.refusals = 0

    def admit(self, client: str, now: float | None = None) -> float:
        """Take one token for ``client``.  Returns 0.0 on admission,
        else the truthful retry_after_s of the refusal."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            b = self._buckets.get(client)
            if b is None:
                if len(self._buckets) >= self.max_clients:
                    self._evict(now)
                b = self._buckets[client] = [self.burst, now]
            tokens = min(self.burst, b[0] + (now - b[1]) * self.rate)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                return 0.0
            b[0] = tokens
            self.refusals += 1
            return max(0.001, round((1.0 - tokens) / self.rate, 3))

    def _evict(self, now: float) -> None:
        # caller holds the lock
        full = [k for k, b in self._buckets.items()
                if min(self.burst, b[0] + (now - b[1]) * self.rate)
                >= self.burst]
        if full:
            for k in full:
                del self._buckets[k]
            return
        self._buckets.pop(next(iter(self._buckets)))
