"""Synthetic canary probes (``serve --canary-interval=S``).

The metrics PRs 6/11 built only describe traffic that HAPPENS: a
silently-wedged lane on an idle daemon looks exactly like a healthy
idle daemon until a user job fails.  The canary closes that gap
(ISSUE 14): every ``S`` seconds the daemon runs the tiny
deterministic warmup corpus (``cli.warmup_files`` — the same files
the PR 13 ``--warmup`` path compiles against) through the NORMAL
serving machinery — a device lease on a free lane, the injected
runner (``cli.run``), a real report written to a daemon-private
directory — and **byte-verifies** the report against a golden digest
captured on the first successful probe.  A bad exit code or a digest
drift flips ``pwasm_canary_ok`` to 0, which the default
``canary_failing`` SLO rule (obs/catalog.py) turns into a page-
severity firing — black-box proof the probe→lease→device→report path
works end to end, continuously, without waiting for a user job to be
the probe.

Mechanics worth knowing:

- **free lane only**: the lease grab uses a short timeout — a tick
  with every lane busy is counted ``skipped``, never queued behind a
  real job (busy lanes are self-evidently serving; the canary exists
  for the idle-but-broken case);
- **device path**: the probe runs ``--device=<warmup device>`` (the
  ``--warmup`` value, default ``tpu``) so the supervised device path
  — probe, breaker, compile cache — is exercised; an injected or
  real backend outage therefore lands on the lane's warm breaker
  state and fires the ``breaker_open`` rule even when the probe's
  own bytes survive via host fallback (the resilience contract);
- **observability, not traffic**: canary runs never touch the job
  table, the journal, the fair-share queue or the run-metric fold —
  they exist only in the ``pwasm_canary_*`` families, the event log
  (``canary_ok``/``canary_fail``) and their own trace ids (stamped
  as exemplars on the canary wall histogram);
- ``PWASM_CANARY_FAULTS="LO-HI:SPEC"`` (debug, the bench's outage
  injector): canary runs numbered LO..HI (1-based) append
  ``--inject-faults=SPEC`` — how the detection-latency bench leg
  scripts an outage window without killing anything real.

jax-free like the rest of ``pwasm_tpu/service/`` (gated by
``qa/check_supervision.py::find_slo_violations``): the device is
reached only through the injected runner.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

# how long a tick waits for a free lane before counting itself
# skipped (a reservation would starve real jobs; see module doc)
LANE_GRAB_S = 0.05


def parse_canary_faults(spec: str | None):
    """``"LO-HI:SPEC"`` -> ``(lo, hi, spec)`` or None — the debug
    window of canary run numbers (1-based, inclusive) that carry an
    ``--inject-faults`` spec.  Malformed values are ignored (a debug
    knob must never take the daemon down)."""
    if not spec or ":" not in spec:
        return None
    window, _, fault = spec.partition(":")
    lo, _, hi = window.partition("-")
    try:
        lo_i, hi_i = int(lo), int(hi or lo)
    except ValueError:
        return None
    if lo_i < 1 or hi_i < lo_i or not fault:
        return None
    return (lo_i, hi_i, fault)


class CanaryRunner:
    """The canary loop for one serve daemon.  ``daemon`` supplies the
    pieces (leases, runner, warm context, obs, jobdir); ``metrics``
    is the ``build_canary_metrics`` dict.  Runs on its own thread
    (started by ``Daemon.serve``), exits when the daemon closes or
    drains.  Never raises — a failing canary is a METRIC, not a
    crashed monitor."""

    def __init__(self, daemon, interval_s: float, metrics: dict):
        self.daemon = daemon
        self.interval_s = max(0.01, float(interval_s))
        self.metrics = metrics
        self.golden: str | None = None
        self.runs = 0
        self.fails = 0
        self.skips = 0
        self.last_ok: bool | None = None
        self.last_wall_s: float | None = None
        self.last_detail = ""
        self.last_t: float | None = None
        self._faults = parse_canary_faults(
            os.environ.get("PWASM_CANARY_FAULTS"))
        self._dir: str | None = None
        self._argv_base: list[str] | None = None
        self._lock = threading.Lock()

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.loop, daemon=True,
                             name="pwasm-svc-canary")
        t.start()
        return t

    def _stopping(self) -> bool:
        d = self.daemon
        return d._closing.is_set() or d.drain.requested

    def loop(self) -> None:
        # one full interval before the first probe: daemon startup
        # (journal replay, warmup) owns the first moments
        while not self._stopping():
            if self.daemon._closing.wait(self.interval_s):
                return
            if self._stopping():
                return
            try:
                self.run_once()
            except Exception as e:     # the never-raises contract
                self._record(False, 0.0, f"canary runner error: {e}")

    # ---- one probe -----------------------------------------------------
    def _ensure_corpus(self) -> list[str]:
        """The deterministic probe argv, built once: warmup corpus +
        a daemon-private output path (never a user path — canary runs
        are observability, byte-invisible to real traffic)."""
        if self._argv_base is not None:
            return list(self._argv_base)
        from pwasm_tpu.cli import warmup_files
        d = self.daemon
        self._dir = os.path.join(d._jobdir.name, "canary")
        paf, fa = warmup_files(self._dir)
        out = os.path.join(self._dir, "canary.dfa")
        device = d.warmup if d.warmup in ("cpu", "tpu") else "tpu"
        self._argv_base = [paf, "-r", fa, "-o", out,
                           f"--device={device}", "--batch=8"]
        return list(self._argv_base)

    def _digest(self) -> str:
        out = os.path.join(self._dir, "canary.dfa")
        try:
            with open(out, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return "missing"

    def run_once(self) -> bool | None:
        """One probe: lease a free lane (or skip), run the corpus,
        verify rc + golden digest, record.  Returns ok/None
        (skipped)."""
        import io

        from pwasm_tpu.obs.events import new_run_id
        from pwasm_tpu.resilience.lifecycle import SignalDrain
        from pwasm_tpu.service.daemon import _JobWarm
        d = self.daemon
        lease = d.leases.acquire(timeout=LANE_GRAB_S,
                                 should_abort=self._stopping)
        if lease is None:
            with self._lock:
                self.skips += 1
            self.metrics["runs"].inc(outcome="skipped")
            return None
        t0 = time.monotonic()
        cid = "canary-" + new_run_id()
        try:
            argv = self._ensure_corpus()
            run_no = self.runs + 1
            if self._faults is not None:
                lo, hi, spec = self._faults
                if lo <= run_no <= hi:
                    argv.append(f"--inject-faults={spec}")
            drain = SignalDrain(stderr=d.stderr,
                                hard_exit=lambda code: None)
            warm = _JobWarm(d.warm, drain, lease,
                            expose_devices=d._expose_devices,
                            trace_id=cid)
            err = io.StringIO()
            try:
                rc = d._runner(argv, stdout=io.StringIO(),
                               stderr=err, warm=warm)
            except BaseException as e:
                rc = None
                err.write(f"canary raised {type(e).__name__}: {e}")
            wall = time.monotonic() - t0
            if rc != 0:
                detail = (f"canary exit {rc}: "
                          + err.getvalue()[-300:].strip())
                return self._record(False, wall, detail, cid)
            digest = self._digest()
            if self.golden is None:
                self.golden = digest
            if digest != self.golden:
                return self._record(
                    False, wall,
                    f"report digest drift: {digest[:16]} != golden "
                    f"{self.golden[:16]}", cid)
            return self._record(True, wall, "", cid)
        finally:
            d.leases.release(lease)

    def _record(self, ok: bool, wall: float, detail: str,
                trace_id: str | None = None) -> bool:
        d = self.daemon
        with self._lock:
            self.runs += 1
            if not ok:
                self.fails += 1
            self.last_ok = ok
            self.last_wall_s = round(wall, 6)
            self.last_detail = detail
            self.last_t = time.time()
        self.metrics["ok"].set(1 if ok else 0)
        self.metrics["wall_seconds"].observe(wall, trace_id=trace_id)
        self.metrics["runs"].inc(outcome="ok" if ok else "fail")
        d.obs.event("canary_ok" if ok else "canary_fail",
                    wall_s=round(wall, 6), run=self.runs,
                    trace_id=trace_id, detail=detail or None)
        return ok

    # ---- introspection (the health verb's canary block) ---------------
    def summary(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "runs": self.runs,
                "fails": self.fails,
                "skipped": self.skips,
                "last_ok": self.last_ok,
                "last_wall_s": self.last_wall_s,
                "last_detail": self.last_detail or None,
                "last_t": round(self.last_t, 3)
                if self.last_t else None,
            }
