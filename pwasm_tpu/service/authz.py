"""Scoped capability tokens for the fleet edge (ISSUE 19).

``serve --auth-tokens=FILE`` / ``route --auth-tokens=FILE`` arm this
module: the file maps *principals* to capability scopes, and every
protocol frame must then carry a principal whose scopes cover its
verb.  Without the flag nothing here runs and every verb stays open —
byte-identical to the pre-auth daemon (drilled by the tier-1
byte-parity tests).

Principals (the keys of the token file):

- a bare token string — presented by clients via ``--client-token``
  (the ``client_token`` frame field);
- ``cn:<name>`` — an mTLS-attested peer certificate CN
  (``--tls-client-ca`` listeners): the connection itself is the
  credential, no frame field needed;
- ``uid:<n>`` — a kernel-attested unix-socket peer uid;
- ``*`` — the default entry for frames with no recognized credential
  (set it to ``["submit", "read"]`` to keep the data plane open while
  locking the control plane).

Scopes: ``submit`` (submit/stream admission), ``read``
(status/result/inspect/stats/metrics/health/logs/cache-probe),
``cancel-own`` (cancel jobs whose resolved client identity matches
yours), ``admin`` (everything, including the verbs that can take the
fleet down: ``drain``, ``lease-grant``, ``fence``, cancel-any).

The file is JSON with the ckpt-v2 integrity rule: a ``crc`` field
(``fsio.payload_crc`` over the rest) so a torn write is DETECTED and
the last good policy kept, never half-applied.  It hot-reloads on the
daemon's existing 0.2 s accept-loop tick (mtime/size change), so
rotating a token needs no restart; an unreadable or corrupt reload
keeps the previous policy and warns — degrading OPEN on a bad file
would be the one wrong answer.

An unauthorized frame answers the truthful ``unauthorized`` error
having changed no queue/journal/lease state, and repeated failures
from one peer trip :class:`PenaltyBox` — a capped-exponential
connection-level delay (brute-force damping) surfaced as
``pwasm_transport_auth_failures_total{client=...}`` plus the
``auth_failure_burst`` SLO rule.
"""

from __future__ import annotations

import json
import os
import threading

SCOPE_SUBMIT = "submit"
SCOPE_READ = "read"
SCOPE_CANCEL_OWN = "cancel-own"
SCOPE_ADMIN = "admin"
ALL_SCOPES = frozenset((SCOPE_SUBMIT, SCOPE_READ, SCOPE_CANCEL_OWN,
                        SCOPE_ADMIN))

# verb -> required scope (None = open: liveness must stay probeable).
# SCOPE_CANCEL_OWN is special-cased by the caller — ownership needs
# the job row, which only the dispatch site holds.  A ``stats`` frame
# carrying a ``lease`` object is a lease grant riding the heartbeat
# (ISSUE 16) and is promoted to admin by required_scope().
VERB_SCOPES: dict[str, str | None] = {
    "ping": None,
    "submit": SCOPE_SUBMIT,
    "stream": SCOPE_SUBMIT,
    "stream-data": SCOPE_SUBMIT,
    "stream-end": SCOPE_SUBMIT,
    "status": SCOPE_READ,
    "result": SCOPE_READ,
    "inspect": SCOPE_READ,
    "stats": SCOPE_READ,
    "metrics": SCOPE_READ,
    "health": SCOPE_READ,
    "logs": SCOPE_READ,
    "cache-probe": SCOPE_READ,
    "cancel": SCOPE_CANCEL_OWN,
    "drain": SCOPE_ADMIN,
    "lease-grant": SCOPE_ADMIN,
    "fence": SCOPE_ADMIN,
}


def required_scope(cmd, req: dict) -> str | None:
    """The scope ``cmd`` needs (None = open, including unknown verbs
    — those answer ``unknown_cmd``, which changes nothing and leaks
    nothing).  A ``stats`` frame carrying a lease heartbeat is a
    lease GRANT and needs admin like the standalone verb."""
    scope = VERB_SCOPES.get(cmd)
    if cmd == "stats" and req.get("lease") is not None:
        return SCOPE_ADMIN
    return scope


def write_auth_tokens(path: str, tokens: dict) -> None:
    """Mint a token file: ``{principal: [scope, ...]}`` stamped with
    the integrity CRC, written durably (fsio) so a crash mid-rotation
    leaves either the old file or the new one, never a torn hybrid."""
    from pwasm_tpu.utils.fsio import payload_crc, write_durable_text
    payload = {"tokens": {str(k): sorted(set(v))
                          for k, v in tokens.items()}}
    payload["crc"] = payload_crc(payload)
    write_durable_text(path, json.dumps(payload, sort_keys=True,
                                        separators=(",", ":")) + "\n")


def _parse_tokens(path: str) -> dict[str, frozenset]:
    """Load and validate a token file; raises ValueError on ANY
    defect (shape, unknown scope, CRC mismatch) — the caller decides
    whether that is fatal (startup) or keep-last-good (reload)."""
    from pwasm_tpu.utils.fsio import payload_crc
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read auth-tokens file {path}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"auth-tokens file {path} is not JSON: {e}")
    if not isinstance(obj, dict) or "crc" not in obj \
            or not isinstance(obj.get("tokens"), dict):
        raise ValueError(
            f"auth-tokens file {path} must be an object "
            '{"tokens": {principal: [scope, ...]}, "crc": N}')
    crc = obj.pop("crc")
    if payload_crc(obj) != crc:
        raise ValueError(
            f"auth-tokens file {path} failed its integrity CRC "
            "(torn or hand-edited write) — re-mint it")
    out: dict[str, frozenset] = {}
    for principal, scopes in obj["tokens"].items():
        if not isinstance(principal, str) or not principal:
            raise ValueError(
                f"auth-tokens file {path}: empty principal")
        if not isinstance(scopes, list) \
                or not all(isinstance(s, str) for s in scopes):
            raise ValueError(
                f"auth-tokens file {path}: scopes for "
                f"{principal!r} must be a list of strings")
        bad = sorted(set(scopes) - ALL_SCOPES)
        if bad:
            raise ValueError(
                f"auth-tokens file {path}: unknown scope(s) "
                f"{bad} for {principal!r} (valid: "
                f"{sorted(ALL_SCOPES)})")
        out[principal] = frozenset(scopes)
    return out


class AuthRegistry:
    """The live scoped-token policy: strict load at startup,
    keep-last-good hot reload on the accept-loop tick."""

    def __init__(self, path: str, say=None):
        self.path = path
        self._say = say          # warning sink (daemon._say shaped)
        self._lock = threading.Lock()
        self._scopes = _parse_tokens(path)   # startup: fail fast
        self._sig = self._stat_sig()
        self._warned_sig = None  # one warning per bad generation
        self.reloads = 0

    def _stat_sig(self):
        try:
            st = os.stat(self.path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def maybe_reload(self) -> None:
        """Called from the accept-loop tick: swap in a changed file's
        policy atomically, keep the last good one (warn once per bad
        generation) when the new bytes don't validate."""
        sig = self._stat_sig()
        if sig == self._sig:
            return
        try:
            scopes = _parse_tokens(self.path)
        except ValueError as e:
            if sig != self._warned_sig:
                self._warned_sig = sig
                if self._say is not None:
                    self._say(f"warning: auth-tokens reload refused "
                              f"({e}); keeping the previous policy")
            self._sig = sig   # don't re-parse the same bad bytes
            #                   every 0.2 s tick — only on next change
            return
        with self._lock:
            self._scopes = scopes
            self._sig = sig
            self._warned_sig = None
            self.reloads += 1
        if self._say is not None:
            self._say(f"auth-tokens reloaded from {self.path} "
                      f"({len(scopes)} principal(s))")

    def scopes_for(self, token, peer) -> frozenset:
        """Union of the scopes granted to every credential the frame
        presents: its ``client_token``, the connection's attested
        peer principal (``cn:<name>`` / ``uid:<n>``), and the ``*``
        default entry."""
        with self._lock:
            scopes = self._scopes
        out: set = set()
        if isinstance(token, str) and token:
            out |= scopes.get(token, frozenset())
        if isinstance(peer, str) and peer:
            out |= scopes.get(peer, frozenset())
        out |= scopes.get("*", frozenset())
        return frozenset(out)

    def allows(self, req: dict, peer, scope: str) -> bool:
        """True when the frame's credentials carry ``scope`` (admin
        implies every scope)."""
        got = self.scopes_for(req.get("client_token"), peer)
        return scope in got or SCOPE_ADMIN in got


class PenaltyBox:
    """Brute-force damping: consecutive auth failures from one peer
    earn a capped-exponential delay (served in that connection's own
    thread — the accept loop never blocks).  A success clears the
    peer's debt.  The table is bounded: past ``max_peers`` the oldest
    entry is evicted, so an attacker spraying identities costs memory
    O(max_peers), not O(attempts)."""

    def __init__(self, base_s: float = 0.05, cap_s: float = 2.0,
                 max_peers: int = 1024):
        self.base_s = base_s
        self.cap_s = cap_s
        self.max_peers = max_peers
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def fail(self, key: str) -> float:
        """Record one failure for ``key``; returns the delay (s) the
        refusal should be held for."""
        with self._lock:
            if key not in self._counts \
                    and len(self._counts) >= self.max_peers:
                self._counts.pop(next(iter(self._counts)))
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
        return min(self.cap_s, self.base_s * (2 ** (n - 1)))

    def clear(self, key: str) -> None:
        with self._lock:
            self._counts.pop(key, None)
