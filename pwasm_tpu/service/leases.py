"""Device-lease scheduler for the warm-pool daemon (ROADMAP item 1).

The daemon used to pin ``--max-concurrent=1`` *on device* because two
concurrent jobs would interleave their programs on one chip — a v5e-8
left 7 chips idle.  This module is the missing layer: the device
inventory is partitioned into **lanes** (``devices_per_lease`` chips
each), every running job holds exactly one :class:`DeviceLease`, and a
job that cannot get a lease WAITS — admission is lease-aware, not just
worker-thread-aware.

Like every ``pwasm_tpu/service/`` module this file is jax-free (the
static gate in ``qa/check_supervision.py`` enforces it): a lease names
a *span of device indices* ``[device_lo, device_hi)`` into the
canonical ``jax.devices()`` order, and the served job's ``cli.run`` —
the only layer allowed to touch jax — maps the span onto real devices
(clamping when fewer exist, e.g. the single-CPU test backend, where a
lease degrades to a plain concurrency token).

What ELSE rides the lease: the per-lane warm state.  PR 5 carried ONE
breaker/ceiling snapshot and ONE health monitor for the whole daemon —
correct when jobs were serial, but with K lanes a flap on lane 0's
chip must not degrade lane 1's healthy chip.  So the supervisor
snapshot and the monitor now live ON the lease (exclusive while a job
holds it, inherited by the NEXT job on the same lane), and the daemon
reports a roll-up (worst lane) for its single breaker gauge plus a
per-lane gauge vector.

Fairness: grants are strict FIFO over waiters (a ticket queue, not a
bare ``Condition`` — ``notify`` order is unspecified, and a starved
submitter is an SLO violation, not a scheduling detail).
"""

from __future__ import annotations

import threading
import time
from collections import deque


class DeviceLease:
    """One lane of the device inventory plus its warm state.

    ``lane``                 0-based lane index;
    ``device_lo/device_hi``  the half-open span of device indices this
                             lane owns (``jax.devices()`` order);
    ``supervisor_state``     the breaker/ceiling snapshot exported by
                             the LAST job that ran on this lane
                             (``BatchSupervisor.export_state`` minus
                             the fault clock);
    ``monitor``              the lane's ``BackendHealthMonitor`` (one
                             re-probe schedule per lane);
    ``jobs_run``             completed grants, for the lane gauges.

    No lock: between ``acquire`` and ``release`` the holder owns the
    object exclusively; the manager's lock covers the free/busy flip.
    """

    def __init__(self, lane: int, device_lo: int, device_hi: int):
        self.lane = lane
        self.device_lo = device_lo
        self.device_hi = device_hi
        self.supervisor_state: dict | None = None
        self.monitor = None
        self.jobs_run = 0
        self.busy = False
        self.busy_s_total = 0.0    # cumulative leased wall (the
        #   per-lane busy-fraction gauge source, ISSUE 11)
        self.granted_at = 0.0      # monotonic grant time while busy

    @property
    def devices(self) -> tuple[int, int]:
        return (self.device_lo, self.device_hi)

    def __repr__(self) -> str:  # debug/log friendliness
        return (f"DeviceLease(lane={self.lane}, "
                f"devices=[{self.device_lo},{self.device_hi}), "
                f"busy={self.busy})")


class _Waiter:
    """One FIFO ticket: ``box`` is filled with the granted lease (or
    None on drain) before ``event`` is set."""

    __slots__ = ("event", "box")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.box: DeviceLease | None = None


class LeaseManager:
    """Thread-safe FIFO lease pool over ``n_lanes`` lanes of
    ``devices_per_lease`` device indices each."""

    def __init__(self, n_lanes: int, devices_per_lease: int = 1):
        self.n_lanes = max(1, int(n_lanes))
        self.devices_per_lease = max(1, int(devices_per_lease))
        self._leases = [
            DeviceLease(i, i * self.devices_per_lease,
                        (i + 1) * self.devices_per_lease)
            for i in range(self.n_lanes)]
        self._free: deque[DeviceLease] = deque(self._leases)
        self._waiters: deque[_Waiter] = deque()
        self._lock = threading.Lock()
        self._draining = False
        self.grants = 0          # cumulative, for stats
        self.wait_s_total = 0.0  # cumulative lease-wait wall

    # ---- introspection (gauges/stats read these) -----------------------
    @property
    def draining(self) -> bool:
        return self._draining

    def leases(self) -> list[DeviceLease]:
        return list(self._leases)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def busy_count(self) -> int:
        with self._lock:
            return self.n_lanes - len(self._free)

    def waiting_count(self) -> int:
        with self._lock:
            return len(self._waiters)

    # ---- grant/release -------------------------------------------------
    def acquire(self, timeout: float | None = None,
                should_abort=None,
                poll_s: float = 0.25,
                prefer_lane: int | None = None) -> DeviceLease | None:
        """Grant the next free lease, FIFO among callers.  Returns None
        on timeout, once :meth:`drain` latched, or when
        ``should_abort()`` turns true mid-wait.  (Wait observability:
        the caller times the call itself — the daemon feeds its
        lease-wait histogram that way, including zero-wait grants —
        and ``wait_s_total`` aggregates the queued waits here.)

        ``prefer_lane`` is an AFFINITY HINT, not a reservation: when
        that lane is free it is granted (a journal-recovered job goes
        back to the lane it ran on, inheriting that lane's warm
        breaker/ceiling state instead of polluting a neighbor's);
        when it is busy — or the caller had to queue — any lane
        serves, because byte output is placement-independent and a
        hard reservation would let one recovered job idle a whole
        pool behind it.

        The ONE ticket enqueued here survives the whole wait —
        ``should_abort`` is polled every ``poll_s`` on the same ticket
        rather than the caller looping short-timeout acquires, because
        a timeout withdraws the ticket and a fresh call re-enqueues at
        the BACK, silently reordering two waiting callers (the exact
        starvation the FIFO queue exists to prevent) and clipping the
        recorded wait to the final slice."""
        t0 = time.monotonic()
        with self._lock:
            if self._draining:
                return None
            if self._free and not self._waiters:
                lease = None
                if prefer_lane is not None:
                    for cand in self._free:
                        if cand.lane == prefer_lane:
                            lease = cand
                            self._free.remove(cand)
                            break
                if lease is None:
                    lease = self._free.popleft()
                lease.busy = True
                lease.granted_at = time.monotonic()
                self.grants += 1
                return lease
            w = _Waiter()
            self._waiters.append(w)
        deadline = None if timeout is None else t0 + timeout
        while True:
            if deadline is None:
                slice_t = poll_s if should_abort is not None else None
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    ok = False
                    break
                slice_t = min(poll_s, remaining) \
                    if should_abort is not None else remaining
            ok = w.event.wait(slice_t)
            if ok:
                break
            if should_abort is not None and should_abort():
                break
            if deadline is not None \
                    and time.monotonic() >= deadline:
                break
        waited = time.monotonic() - t0
        with self._lock:
            if w.box is None:
                # timed out (aborted, or drained): withdraw the
                # ticket; a grant racing this withdrawal filled the
                # box first and wins
                try:
                    self._waiters.remove(w)
                except ValueError:
                    pass
                if w.box is None:
                    return None
            lease = w.box
            self.grants += 1
            self.wait_s_total += waited
        return lease

    def release(self, lease: DeviceLease) -> None:
        """Return ``lease`` to the pool, handing it straight to the
        oldest waiter if one queued (FIFO — the starvation guard)."""
        with self._lock:
            lease.busy = False
            lease.busy_s_total += max(
                0.0, time.monotonic() - lease.granted_at)
            lease.jobs_run += 1
            while self._waiters:
                w = self._waiters.popleft()
                if not w.event.is_set():
                    lease.busy = True
                    lease.granted_at = time.monotonic()
                    w.box = lease
                    w.event.set()
                    return
            if lease not in self._free:
                self._free.append(lease)

    def drain(self) -> None:
        """Latch: every queued and future ``acquire`` returns None.
        Leases already granted stay valid until released (the in-flight
        jobs finish at their batch boundaries)."""
        with self._lock:
            self._draining = True
            waiters, self._waiters = list(self._waiters), deque()
        for w in waiters:
            w.event.set()      # box stays None: "no lease, drained"

    # ---- roll-ups ------------------------------------------------------
    def breaker_rollup(self) -> int:
        """Worst breaker state over all lanes (0 closed, 1 half-open,
        2 open — the daemon-level gauge encoding): one number for the
        operator's 'is anything degraded' glance, with the per-lane
        gauge vector carrying the which.  Derived from the SAME
        locked snapshot as :meth:`lane_states` so the roll-up gauge
        can never disagree with max() over the per-lane vector within
        one scrape."""
        return max((r["breaker_state"] for r in self.lane_states()),
                   default=0)

    def lane_states(self) -> list[dict]:
        """Per-lane stats rows (the svc-stats ``lanes`` block)."""
        from pwasm_tpu.obs.catalog import breaker_state_value
        out = []
        now = time.monotonic()
        with self._lock:
            for lease in self._leases:
                st = lease.supervisor_state
                mon = lease.monitor
                busy_s = lease.busy_s_total
                if lease.busy:
                    # include the CURRENT grant's elapsed time, so a
                    # long-running job shows as busy wall, not zero
                    busy_s += max(0.0, now - lease.granted_at)
                out.append({
                    "lane": lease.lane,
                    "devices": [lease.device_lo, lease.device_hi],
                    "busy": lease.busy,
                    "jobs_run": lease.jobs_run,
                    "busy_s": round(busy_s, 3),
                    "breaker_state": breaker_state_value(
                        bool(st.get("breaker_open")) if st else False,
                        mon.state if mon is not None else None),
                })
        return out
