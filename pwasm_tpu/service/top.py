"""``pwasm-tpu top`` — live fleet introspection over one socket.

A refresh-loop terminal view rendered from the daemon's ``stats``
response (the SAME registry-backed svc-stats surface ``pwasm-tpu
svc-stats`` prints as JSON, so the two cannot disagree): device-lease
lanes with busy fraction and breaker state, queued jobs per fair-share
client, live streams with buffer lag, and the job-outcome counters.
One screen answers the operator's first three incident questions —
is anything degraded, who is queued, is a stream backing up — without
leaving the terminal.

``--once`` renders a single frame and exits (the scriptable/testable
form; the refresh loop just repaints it).  Rendering is a pure
function of the stats dict (:func:`render`), unit-tested directly.

Like every ``pwasm_tpu/service/`` module this file is jax-free
(``qa/check_supervision.py``).
"""

from __future__ import annotations

import time

from pwasm_tpu.core.errors import EXIT_FATAL, EXIT_USAGE

_TOP_USAGE = """Usage:
 pwasm-tpu top --socket=TARGET [--interval=S] [--once]

   --socket=TARGET the serve daemon's unix socket, a HOST:PORT TCP
                   endpoint, or a fleet router (`pwasm-tpu route`) —
                   against a router the view is fleet-aware: member
                   liveness/load rows ride above the aggregated
                   queue/stream/job sections (docs/FLEET.md)
   --interval=S    refresh period in seconds (default 2)
   --once          render one frame and exit (no screen clearing)

 Ctrl-C exits.  The view is rendered from the daemon's svc-stats
 response (docs/OBSERVABILITY.md).
"""

_BREAKER_NAMES = {0: "closed", 1: "HALF-OPEN", 2: "OPEN"}


def _fmt_breaker(v) -> str:
    return _BREAKER_NAMES.get(v, str(v))


def render(st: dict) -> str:
    """One ``top`` frame from a svc-stats dict — pure and total:
    missing blocks render as empty sections, never a crash (an older
    daemon's stats must still display)."""
    out: list[str] = []
    jobs = st.get("jobs") or {}
    fleet = st.get("fleet") or {}
    out.append(
        ("pwasm-tpu top (FLEET)" if fleet else "pwasm-tpu top")
        + f" — uptime {st.get('uptime_s', 0):.0f}s"
        + ("  [DRAINING]" if st.get("draining") else "")
        + f"  breaker {_fmt_breaker(st.get('breaker_state', 0))}")
    if fleet:
        # fleet-aware view (the `route` daemon's aggregated stats):
        # one row per member daemon, liveness first — "is anything
        # down" is the fleet operator's question zero
        members = fleet.get("members") or []
        out.append(
            f" fleet: {fleet.get('alive', 0)}/{len(members)} members "
            f"up | routed {fleet.get('jobs_routed', 0)}  live "
            f"{fleet.get('live_jobs', 0)}  failovers "
            f"{fleet.get('failovers', 0)}")
        shed = (st.get("ha") or {}).get("shed") or {}
        if shed.get("level"):
            # the brownout banner (ISSUE 18): the operator must see
            # turned-away tiers before reading any member row
            out.append(
                " SHEDDING: tier(s) "
                + (",".join(shed.get("lanes_shed") or []) or "?")
                + f" turned away (level {shed.get('level')})")
        out.append(" MEMBER                 STATE  DEPTH  RUN  ROUTED"
                   "    LAT")
        for row in members:
            alive = row.get("alive")
            # one word, worst condition first: a quarantined (gray)
            # or fenced member is "up" but taking no placements —
            # rendering it as plain up hides the exact state this
            # view exists to surface
            state = ("DOWN" if not alive
                     else "QUAR" if row.get("quarantined")
                     else "FENC" if row.get("fenced") else "up")
            lat = row.get("lat_ewma_ms")
            out.append(
                f"   {str(row.get('name', '?')):<20} "
                + f"{state:>5}  "
                + (f"{row.get('queue_depth', 0) or 0:>5}  "
                   f"{row.get('running', 0) or 0:>3}  "
                   if alive else "    -    -  ")
                + f"{row.get('jobs_routed', 0):>6}  "
                + (f"{lat:>5.0f}" if isinstance(lat, (int, float))
                   and alive else "    -"))
        rec = fleet.get("jobs_recovered") or {}
        recovered = {k: v for k, v in sorted(rec.items()) if v}
        if recovered:
            out.append(" recovered: " + "  ".join(
                f"{k} {v}" for k, v in recovered.items()))
    # the alerts pane (ISSUE 14): the SLO engine's verdict + firing
    # rules, from the same health block the `health` verb serves —
    # "is anything wrong" before any counter reading.  On a fleet
    # view the verdict can be degraded/failing through a MEMBER's own
    # rules while the router's are all quiet — those members render
    # here too, or the pane would say "none" under a failing verdict.
    health = st.get("health") or {}
    firing = health.get("firing") or []
    bad_members = {n: m for n, m in
                   (health.get("members") or {}).items()
                   if isinstance(m, dict)
                   and m.get("verdict") not in ("ok", None)}
    if health:
        parts = [
            f"{f.get('rule', '?')}[{f.get('severity', '?')}"
            + (f" {f.get('since_s', 0):.0f}s" if f.get("since_s")
               else "") + "]"
            for f in firing if isinstance(f, dict)]
        parts += [
            f"{n}={m.get('verdict')}"
            + (f"({','.join(str(r) for r in m.get('firing'))})"
               if m.get("firing") else "")
            for n, m in sorted(bad_members.items())]
        if parts or health.get("verdict", "ok") != "ok":
            out.append(
                f" ALERTS ({health.get('verdict', '?')}): "
                + ("  ".join(parts) if parts else "(see members)"))
        else:
            out.append(" ALERTS: none")
    canary = health.get("canary") or {}
    if canary.get("runs"):
        ok = canary.get("last_ok")
        out.append(
            f" canary: {'ok' if ok else 'FAILING'} "
            f"({canary.get('runs', 0)} runs, "
            f"{canary.get('fails', 0)} fails, last "
            f"{canary.get('last_wall_s') or 0:.3f}s)")
    out.append(
        f" jobs: {st.get('running', 0)} running, "
        f"{st.get('queue_depth', 0)} queued | "
        f"done {jobs.get('completed', 0)}  "
        f"failed {jobs.get('failed', 0)}  "
        f"preempted {jobs.get('preempted', 0)}  "
        f"cancelled {jobs.get('cancelled', 0)}  "
        f"rejected {jobs.get('rejected', 0)}  "
        f"recovered {jobs.get('recovered', 0)}")
    lanes = st.get("lanes") or []
    if lanes:
        uptime = max(1e-9, float(st.get("uptime_s") or 0) or 1e-9)
        out.append("")
        out.append(" LANE  DEVICES   STATE  JOBS  BUSY%  BREAKER")
        for row in lanes:
            dev = row.get("devices") or [0, 0]
            busy_pct = 100.0 * min(
                1.0, float(row.get("busy_s") or 0.0) / uptime)
            out.append(
                f" {row.get('lane', '?'):>4}  "
                f"[{dev[0]},{dev[1]}) ".ljust(10)
                + f"{'busy' if row.get('busy') else 'idle':>5}  "
                f"{row.get('jobs_run', 0):>4}  "
                f"{busy_pct:>4.0f}%  "
                f"{_fmt_breaker(row.get('breaker_state', 0))}")
    fair = st.get("fair_share") or {}
    clients = fair.get("clients") or {}
    queued = {c: n for c, n in sorted(clients.items()) if n}
    out.append("")
    if queued:
        out.append(f" QUEUE by client (quota "
                   f"{fair.get('max_queue_per_client', '?')}/client, "
                   f"{fair.get('max_queue_total', '?')} total):")
        for c, n in queued.items():
            out.append(f"   {c:<24} {n}")
    else:
        out.append(" QUEUE empty")
    streams = st.get("streams") or {}
    if streams.get("active"):
        out.append(
            f" STREAMS: {streams.get('active')} live, "
            f"lag {streams.get('lag_records', 0)}/"
            f"{streams.get('max_buffer_total', '?')} records "
            f"(records in {streams.get('records_in', 0)}, "
            f"batches {streams.get('batches', 0)})")
    else:
        out.append(" STREAMS: none")
    m2m = st.get("m2m") or {}
    if m2m.get("active") or m2m.get("sessions"):
        # continuous surveillance (ISSUE 20): live session flow plus
        # the incremental win — how much of the pair matrix the
        # section cache spliced instead of re-scoring
        pairs = (m2m.get("pairs_dispatched", 0)
                 + m2m.get("pairs_reused", 0))
        ratio = 100.0 * m2m.get("pairs_reused", 0) / pairs \
            if pairs else 0.0
        out.append(
            f" M2M: {m2m.get('active', 0)} live / "
            f"{m2m.get('sessions', 0)} session(s), "
            f"targets {m2m.get('targets_scored', 0)} scored + "
            f"{m2m.get('targets_reused', 0)} reused of "
            f"{m2m.get('targets_in', 0)} | pairs "
            f"{m2m.get('pairs_dispatched', 0)} dispatched, "
            f"{m2m.get('pairs_reused', 0)} spliced "
            f"({ratio:.0f}% reuse), "
            f"{m2m.get('sections_emitted', 0)} section(s)")
    cache = st.get("cache") or {}
    if cache.get("enabled"):
        # the result cache (ISSUE 15): hit flow + on-disk footprint —
        # "is repeat traffic actually landing on the fast path" (and
        # eviction keeping pace with insertion is the cache_thrash
        # page's precursor, visible here first)
        out.append(
            f" CACHE: {cache.get('hits', 0)} hits / "
            f"{cache.get('misses', 0)} misses "
            f"(ratio {100.0 * float(cache.get('hit_ratio') or 0):.0f}"
            f"%) | {cache.get('insertions', 0)} inserted, "
            f"{cache.get('evictions', 0)} evicted, "
            f"{cache.get('bytes', 0)} bytes")
    warm = st.get("warm") or {}
    journal = st.get("journal") or {}
    out.append(
        f" warm hits {warm.get('backend_warm_hits', 0)} / probes "
        f"{warm.get('backend_probes', 0)} | journal "
        f"{'BROKEN' if journal.get('broken') else 'ok'}, "
        f"{journal.get('records', 0)} records, "
        f"{journal.get('replays', 0)} replay(s)")
    return "\n".join(out) + "\n"


def top_main(argv: list[str], stdout=None, stderr=None) -> int:
    """The ``pwasm-tpu top`` entry point."""
    import sys
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    sock = None
    interval = 2.0
    once = False
    for a in argv:
        if a.startswith("--socket="):
            sock = a.split("=", 1)[1]
        elif a.startswith("--interval="):
            import math
            try:
                interval = float(a.split("=", 1)[1])
                if interval <= 0 or not math.isfinite(interval):
                    raise ValueError
            except (TypeError, ValueError):
                stderr.write(f"{_TOP_USAGE}\nInvalid --interval "
                             f"value: {a.split('=', 1)[1]}\n")
                return EXIT_USAGE
        elif a == "--once":
            once = True
        elif a in ("-h", "--help"):
            stderr.write(_TOP_USAGE)
            return EXIT_USAGE
        else:
            stderr.write(f"{_TOP_USAGE}\nInvalid argument: {a}\n")
            return EXIT_USAGE
    if not sock:
        stderr.write(f"{_TOP_USAGE}\nError: --socket=PATH is "
                     "required\n")
        return EXIT_USAGE
    from pwasm_tpu.service.client import ServiceClient, ServiceError
    try:
        while True:
            try:
                with ServiceClient(sock, timeout=10.0) as c:
                    resp = c.stats()
            except ServiceError as e:
                stderr.write(f"Error: {e}\n")
                return EXIT_FATAL
            if not resp.get("ok"):
                stderr.write(f"Error: stats failed: {resp}\n")
                return EXIT_FATAL
            frame = render(resp["stats"])
            if not once:
                stdout.write("\x1b[H\x1b[2J")   # home+clear: repaint
            stdout.write(frame)
            try:
                stdout.flush()
            except Exception:
                pass
            if once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        # "Ctrl-C exits" means exits CLEANLY — wherever it lands (the
        # in-flight stats RPC included), never a traceback
        return 0
