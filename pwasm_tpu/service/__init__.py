"""Warm-pool job service: the resident serving layer (ISSUE 5).

Every CLI invocation is a cold one-shot: it re-pays the interpreter
imports, the bounded backend probe, and the XLA compiles before the
first alignment is touched — the exact cost profile the dispatch-lean
pipeline and the persistent compile cache were built to amortize, but
which nothing amortizes *across* runs.  This package adds the missing
layer between "fast single run" and "serving": one resident daemon
(``pwasm-tpu serve`` == ``python -m pwasm_tpu.cli serve``) that keeps
the process warm and multiplexes report jobs over a unix socket:

- ``protocol``  the newline-delimited-JSON frame format and the error
                vocabulary (``queue_full``, ``draining``, ...);
- ``queue``     the bounded FIFO job queue with admission control and
                the service-level counters;
- ``daemon``    the server: accept loop, worker pool, the shared
                :class:`~pwasm_tpu.service.daemon.WarmContext` every
                job's ``cli.run`` threads through (one backend probe,
                one jit cache, one health monitor + global breaker,
                one drain), and the SIGTERM drain that finishes
                in-flight jobs at batch boundaries and exits 75;
- ``client``    the client side (``pwasm-tpu submit`` /
                ``pwasm-tpu svc-stats``) and the
                :class:`~pwasm_tpu.service.client.ServiceClient`
                library the bench and tests drive.

Jobs execute through the EXISTING ``cli.run`` path, so outputs stay
byte-identical to a cold CLI run — the serve process changes wall
time and counters, never bytes.  See ``docs/SERVICE.md``.
"""

from pwasm_tpu.service.queue import (  # noqa: F401
    JOB_CANCELLED, JOB_DONE, JOB_FAILED, JOB_PREEMPTED, JOB_QUEUED,
    JOB_RUNNING, Draining, Job, JobQueue, QueueFull, ServiceStats)
