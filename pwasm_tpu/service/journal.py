"""Durable job journal for the serve daemon (crash-safe serving).

Everything the daemon used to know only in RAM — which jobs were
admitted, which were granted a lane and started, which finished and
how — dies with a ``kill -9``.  The journal is the daemon's write-ahead
record of exactly that state: one fsync'd NDJSON line per transition
(``admit`` / ``start`` / ``finish`` / ``cancel`` / ``evict``), appended
through :class:`pwasm_tpu.utils.fsio.DurableAppender` (the audited
fsync-per-record primitive; the static gate in
``qa/check_durability.py`` keeps raw fsync out of this layer), so a
daemon restarted on the same socket can :func:`replay` the file and

- **re-queue** jobs that were admitted but never started (their
  admission was acked to the client, so losing them silently would be
  a broken promise);
- **re-admit** jobs that were running as ``--resume`` continuations of
  their own report checkpoints — the ckpt-v2 resume contract makes the
  recovered report byte-identical to a never-crashed run;
- **restore** terminal jobs as result-index entries (rc/state/detail
  from the ``finish`` record, large results from their spool files) so
  a client polling ``result`` across the crash still gets its verdict.

Crash-safety of the journal itself: records are complete lines or they
don't count.  :func:`replay` parses every whole line and tolerates a
torn final line (the kill landed mid-append) — the corresponding
transition simply never happened, which is exactly the write-ahead
contract.  After replay the daemon :meth:`compact`\\ s the file
(atomic ``fsio.write_durable_text`` rewrite holding only the records
that still matter) so restart cost is bounded by live state, not
daemon-lifetime history.

Like every ``pwasm_tpu/service/`` module this file is jax-free (gated
by ``qa/check_supervision.py``).
"""

from __future__ import annotations

import json
import threading

from pwasm_tpu.utils.fsio import DurableAppender, write_durable_text

JOURNAL_VERSION = 1

# the record vocabulary (the "rec" field of every line)
REC_ADMIT = "admit"      # job acked to the client (argv, client, ...)
REC_START = "start"      # job granted a lane and handed to cli.run
REC_FINISH = "finish"    # terminal verdict (state/rc/detail[/spool])
REC_CANCEL = "cancel"    # client requested cancel (queued or running)
REC_EVICT = "evict"      # terminal result dropped (TTL/LRU)
REC_REPLAY = "replay"    # a restart replayed the journal (marker)
REC_CACHE_HIT = "cache_hit"   # job answered from the result cache at
#   admission — it never entered the queue or touched a device, and
#   the record keeps replay accounting truthful: a restarted daemon
#   (or a failover router reading this journal) sees WHY the job has
#   a finish record but no start record

# ── router write-ahead vocabulary (ISSUE 16, fleet/router.py) ──
# The fleet router journals its routed-job table through this same
# JobJournal (same appender, same torn-tail contract, same compaction)
# with its own record kinds, so a kill -9'd router — or the warm
# standby tailing the file — can rebuild every routed admission and
# in-flight placement.  fold_route_records lives in fleet/router.py
# (the fold is routing semantics; this module only owns the durable
# line format).
REC_ROUTE_ADMIT = "route_admit"    # routed job acked (frame, client,
#                                    trace_id, stream flag)
REC_ROUTE_PLACE = "route_place"    # placement or failover RE-placement
#                                    (member, member job id, gen, epoch)
REC_ROUTE_RETIRE = "route_retire"  # routed job retired from the ledger
#                                    (optionally with a router-cached
#                                    terminal verdict: state/rc/detail)
REC_EPOCH = "epoch"                # fleet epoch bump (fencing): every
#                                    failover event and every router
#                                    restart/takeover writes one
REC_MEMBERS = "members"            # member-set snapshot — the standby
#                                    inherits its backends from the
#                                    LAST of these, never from flags
REC_SCALE = "scale"                # scaler action (spawn/retire) with
#                                    the member target + child pid, so
#                                    a restarted router knows which
#                                    members it owns
REC_ROUTE_SHED = "route_shed"      # brownout shed-level transition
#                                    (ISSUE 18): level + lane set, so
#                                    the journal records WHEN the
#                                    router started/stopped turning
#                                    low-priority admissions away
#                                    (fold_route_records skips it —
#                                    shed state is not rebuilt, only
#                                    auditable)


class JobJournal:
    """Append-side of the journal.  Thread-safe: worker threads and
    connection threads append concurrently.  A failed append degrades
    loudly (the ``broken`` latch — the daemon warns once and keeps
    serving without crash-safety) rather than taking the service down:
    a full disk must cost the recovery guarantee, not the fleet."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._appender: DurableAppender | None = None
        self.broken: str | None = None   # first append failure detail
        self.records_written = 0

    def open(self) -> None:
        with self._lock:
            if self._appender is None:
                self._appender = DurableAppender(self.path)

    def append(self, rec: str, **fields) -> bool:
        """Durably append one record; returns False (and latches
        ``broken``) on the first OSError instead of raising."""
        return self.append_many([(rec, fields)])

    def append_many(self, rows: list) -> bool:
        """Durably append several records in ONE write+fsync.  Same
        torn-tail contract as single appends (whole newline-terminated
        lines count, a torn suffix never happened) at one fsync's cost
        — the admission-time cache-hit path journals its
        admit/cache_hit/finish triple through here, so a hit pays one
        disk barrier, not three.  ``rows`` is ``[(rec, fields), ...]``."""
        chunks = []
        for rec, fields in rows:
            obj = {"v": JOURNAL_VERSION, "rec": rec}
            obj.update(fields)
            chunks.append(json.dumps(
                obj, separators=(",", ":")).encode("utf-8") + b"\n")
        data = b"".join(chunks)
        with self._lock:
            if self._appender is None or self.broken is not None:
                return False
            try:
                self._appender.append(data)
            except OSError as e:
                self.broken = str(e)
                return False
            self.records_written += len(rows)
            return True

    def replay(self) -> list[dict]:
        """Parse every COMPLETE record currently in the journal file.
        A final line without its newline — or any unparseable line —
        is skipped: a record torn by the crash never durably happened.
        Returns [] when the file doesn't exist."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return []
        out: list[dict] = []
        for line in raw.split(b"\n")[:-1]:   # drop the torn tail (the
            # slice keeps only newline-TERMINATED records; a whole
            # final line ends in \n so the last split element is b"")
            if not line.strip():
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and isinstance(obj.get("rec"),
                                                    str):
                out.append(obj)
        return out

    def compact(self, records: list[dict]) -> None:
        """Atomically rewrite the journal to exactly ``records`` (the
        post-replay live state) via the audited fsync-then-replace,
        then reopen the appender on the new file.  Crash-safe at any
        instant: the old journal or the new one, never a mix."""
        text = "".join(json.dumps(r, separators=(",", ":")) + "\n"
                       for r in records)
        with self._lock:
            if self._appender is not None:
                self._appender.close()
                self._appender = None
            write_durable_text(self.path, text)
            self._appender = DurableAppender(self.path)
            self.records_written = len(records)

    def close(self) -> None:
        with self._lock:
            if self._appender is not None:
                self._appender.close()
                self._appender = None

    def unlink(self) -> None:
        """Remove the journal (clean-drain exit: every admitted job
        reached a terminal state the clients were told about, so there
        is nothing left to recover)."""
        import os
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


def fold_records(records: list[dict]) -> dict[str, dict]:
    """Fold a replayed record stream into one state row per job id,
    preserving admit order (the ``_ord`` key): ``{"admit": rec,
    "start": rec|None, "finish": rec|None, "cancel": rec|None,
    "evicted": bool}``.  Records for ids with no admit are dropped
    (their admit line was torn, so the admission never durably
    happened and the client was — at worst — never acked)."""
    out: dict[str, dict] = {}
    for rec in records:
        jid = rec.get("job_id")
        kind = rec.get("rec")
        if kind == REC_REPLAY or not isinstance(jid, str):
            continue
        if kind == REC_ADMIT:
            out.setdefault(jid, {"admit": rec, "start": None,
                                 "finish": None, "cancel": None,
                                 "evicted": False, "cache_hit": False,
                                 "_ord": len(out)})
            continue
        row = out.get(jid)
        if row is None:
            continue
        if kind == REC_START:
            row["start"] = rec
        elif kind == REC_FINISH:
            row["finish"] = rec
        elif kind == REC_CANCEL:
            row["cancel"] = rec
        elif kind == REC_EVICT:
            row["evicted"] = True
        elif kind == REC_CACHE_HIT:
            row["cache_hit"] = True
    return out
