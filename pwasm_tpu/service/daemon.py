"""The warm-pool serving daemon (``pwasm-tpu serve``).

One resident process, one unix socket, a bounded FIFO queue, and a
small worker pool executing jobs through the EXISTING ``cli.run`` path
— so a served job's outputs are byte-identical to a cold CLI run of
the same argv.  What the daemon adds is everything a cold run cannot
amortize:

- **one warm process**: imports, the jit/compile caches, and the
  bounded backend probe are paid once — jobs after the first answer
  the probe from warm state (``backend.warm_hits`` in each job's
  ``--stats``, gated by the bench's ``realistic_serve_warm_jobs``);
- **one resilience stack**: the :class:`WarmContext` carries the
  supervisor's breaker/ceiling state and the single
  ``BackendHealthMonitor`` across jobs — a flap that opens the breaker
  in job N leaves it open for job N+1 (no re-trip, no doomed device
  attempts), and a reclose re-promotes every subsequent job;
- **one drain**: the first SIGTERM/SIGINT (or the ``drain`` protocol
  command) latches admission shut, pulls every running job's drain
  flag (each finishes its in-flight batch, checkpoints, and exits 75
  "preempted, resumable"), marks still-queued jobs preempted without
  starting them, and the daemon itself exits 75.  A second signal
  hard-aborts, exactly like the CLI.

Concurrency model: the accept loop and each client connection run on
their own threads; ``--max-concurrent`` worker threads execute jobs.
Worker threads can never install signal handlers
(``SignalDrain.install`` no-ops off the main thread by design), so the
daemon's OWN drain — installed on the main thread — is the one signal
surface, fanned out to per-job drain flags.
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile
import threading
import time

from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE, PwasmError
from pwasm_tpu.resilience.lifecycle import SignalDrain
from pwasm_tpu.service import protocol
from pwasm_tpu.service.queue import (JOB_CANCELLED, JOB_DONE, JOB_FAILED,
                                     JOB_PREEMPTED, JOB_QUEUED,
                                     JOB_RUNNING, TERMINAL_STATES,
                                     Draining, Job, JobQueue, QueueFull,
                                     ServiceStats)

_SERVE_USAGE = """Usage:
 pwasm-tpu serve --socket=PATH [--max-queue=N] [--max-concurrent=N]
                 [--max-frame-bytes=N]

   --socket=PATH        unix socket to listen on (required)
   --max-queue=N        admission control: queued-job ceiling, beyond
                        which submit answers queue_full (default 16)
   --max-concurrent=N   worker threads executing jobs (default 1 —
                        serial jobs share the device cleanly; raise it
                        only for host-path workloads)
   --max-frame-bytes=N  protocol frame ceiling (default 8 MiB)

 SIGTERM/SIGINT (or the `drain` protocol command) drains gracefully:
 in-flight jobs finish at their next batch boundary and checkpoint,
 queued jobs are reported preempted-resumable, new submissions are
 rejected, and the daemon exits 75.  A second signal hard-aborts.
"""


class WarmContext:
    """The state ONE warm process shares across consecutive
    ``cli.run`` invocations.  ``cli.run(..., warm=ctx)`` reads/writes:

    - ``drain``             the SignalDrain the run must honor (the
                            daemon supplies a per-job one via
                            :class:`_JobWarm`);
    - ``monitor``           the single ``BackendHealthMonitor``,
                            re-attached to each job's RunStats;
    - ``supervisor_state``  the breaker/ceiling snapshot exported at
                            each job's end and restored into the next
                            job's supervisor (fault clock stripped —
                            scripted fault windows are per-job).
    """

    def __init__(self) -> None:
        self.drain = None
        self.monitor = None
        self.supervisor_state: dict | None = None
        self.lock = threading.Lock()


class _JobWarm:
    """Per-job view of the shared :class:`WarmContext`: shared
    supervisor state (lock-guarded snapshot swap), this job's own
    drain flag, and the monitor shared ONLY when jobs are serial
    (``--max-concurrent=1``, the device default).  A monitor is one
    probe schedule with per-run sinks — two concurrent jobs calling
    ``attach()`` on it would rebind each other's stats mid-run and
    reset the probe callable under the other's feet, so with a wider
    worker pool each job runs its own monitor and only the
    breaker/ceiling snapshot (an atomic dict swap) is inherited."""

    def __init__(self, shared: WarmContext, drain: SignalDrain,
                 share_monitor: bool = True):
        self._shared = shared
        self.drain = drain
        self._share_monitor = share_monitor
        self._own_monitor = None

    @property
    def monitor(self):
        if self._share_monitor:
            return self._shared.monitor
        return self._own_monitor

    @monitor.setter
    def monitor(self, m) -> None:
        if self._share_monitor:
            self._shared.monitor = m
        else:
            self._own_monitor = m

    @property
    def supervisor_state(self):
        with self._shared.lock:
            return self._shared.supervisor_state

    @supervisor_state.setter
    def supervisor_state(self, st) -> None:
        with self._shared.lock:
            self._shared.supervisor_state = st


class Daemon:
    """The serving daemon.  ``runner`` is injectable for tests and
    defaults to ``pwasm_tpu.cli.run``."""

    def __init__(self, socket_path: str, max_queue: int = 16,
                 max_concurrent: int = 1,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 stderr=None, runner=None):
        self.socket_path = socket_path
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_frame_bytes = int(max_frame_bytes)
        self.stderr = stderr if stderr is not None else sys.stderr
        self._runner = runner
        self.queue = JobQueue(max_queue)
        self.jobs: dict[str, Job] = {}
        self.stats = ServiceStats()
        self.warm = WarmContext()
        self.drain = SignalDrain(stderr=self.stderr)
        self._lock = threading.Lock()
        self._running: dict[str, Job] = {}
        self._draining = False
        self._closing = threading.Event()
        self._next_id = 0
        self._jobdir: tempfile.TemporaryDirectory | None = None
        from collections import deque
        self._job_walls: deque = deque(maxlen=8)  # recent finished-job
        #                       walls (the retry_after_s hint) — only
        #                       the recent window matters, so bounded

    # ---- lifecycle -----------------------------------------------------
    def serve(self) -> int:
        """Bind, accept, and run until drained.  Returns the process
        exit code: 75 after a graceful drain (the daemon's own
        "preempted, resumable" — queued jobs were reported resumable),
        matching the per-job contract."""
        if self._runner is None:
            from pwasm_tpu.cli import run as cli_run
            self._runner = cli_run
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            if os.path.exists(self.socket_path):
                # a stale socket from a dead daemon: binding over it
                # needs the unlink; a LIVE daemon still holds the
                # listener, so connecting first tells the two apart
                if _socket_alive(self.socket_path):
                    raise PwasmError(
                        f"Error: a daemon is already serving on "
                        f"{self.socket_path}\n")
                os.unlink(self.socket_path)
            sock.bind(self.socket_path)
        except OSError as e:
            sock.close()
            raise PwasmError(
                f"Error: cannot bind service socket "
                f"{self.socket_path}: {e}\n")
        sock.listen(16)
        sock.settimeout(0.2)
        self._jobdir = tempfile.TemporaryDirectory(prefix="pwasm_svc_")
        workers = [threading.Thread(target=self._worker, daemon=True,
                                    name=f"pwasm-svc-worker-{i}")
                   for i in range(self.max_concurrent)]
        drained_at: float | None = None
        with self.drain:     # signal handlers (main thread only)
            for w in workers:
                w.start()
            self._say(f"serving on {self.socket_path} "
                      f"(max-queue {self.queue.max_queue}, "
                      f"max-concurrent {self.max_concurrent})")
            try:
                while True:
                    if self.drain.requested:
                        self._begin_drain(self.drain.reason
                                          or "drain requested")
                        if self._drained():
                            # linger briefly so waiters blocked in
                            # `result` get their final frames before
                            # the process goes away
                            if drained_at is None:
                                drained_at = time.monotonic()
                            elif time.monotonic() - drained_at > 0.5:
                                break
                    try:
                        conn, _ = sock.accept()
                    except socket.timeout:
                        continue
                    except OSError:
                        break
                    t = threading.Thread(target=self._handle_conn,
                                         args=(conn,), daemon=True)
                    t.start()
            finally:
                self._closing.set()
                for w in workers:
                    w.join(timeout=5.0)
                sock.close()
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass
                if self._jobdir is not None:
                    self._jobdir.cleanup()
        if self.drain.requested:
            self._say(f"drained — exiting resumable "
                      f"(exit {EXIT_PREEMPTED}); resubmit preempted "
                      "jobs with --resume to complete them")
            return EXIT_PREEMPTED
        return 0

    def _say(self, msg: str) -> None:
        print(f"pwasm: {msg}", file=self.stderr)

    def _drained(self) -> bool:
        with self._lock:
            return self._draining and not self._running \
                and self.queue.depth() == 0

    def _begin_drain(self, reason: str) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            running = list(self._running.values())
        waiting = self.queue.drain()
        for job in waiting:
            job.state = JOB_PREEMPTED
            job.rc = EXIT_PREEMPTED
            job.detail = ("preempted before start (service drained); "
                          "resubmit to a live service — with --resume "
                          "if a previous attempt checkpointed")
            job.finished_s = time.time()
            self.stats.jobs_preempted += 1
            job.done.set()
        for job in running:
            if job.drain is not None:
                job.drain.request(reason)
        self._say(f"draining ({reason}): {len(running)} in-flight "
                  f"job(s) finishing at their batch boundaries, "
                  f"{len(waiting)} queued job(s) preempted, new "
                  "submissions rejected")

    # ---- workers -------------------------------------------------------
    def _worker(self) -> None:
        while not self._closing.is_set():
            job = self.queue.take(timeout=0.1)
            if job is None:
                if self._draining:
                    return
                continue
            with self._lock:
                self._running[job.id] = job
            try:
                self._run_job(job)
            finally:
                with self._lock:
                    self._running.pop(job.id, None)
                job.done.set()

    def _run_job(self, job: Job) -> None:
        job.state = JOB_RUNNING
        job.started_s = time.time()
        # a drain latched between this job's dequeue and here must
        # still reach its flag (the _begin_drain snapshot may have
        # missed it)
        if self.drain.requested and job.drain is not None \
                and not job.drain.requested:
            job.drain.request(self.drain.reason or "service draining")
        warm = _JobWarm(self.warm, job.drain,
                        share_monitor=self.max_concurrent == 1)
        rc: int | None = None
        try:
            rc = self._runner(job.argv, stdout=job.outbuf,
                              stderr=job.errbuf, warm=warm)
        except BaseException as e:   # InjectedKill, stray PwasmError —
            # a dying job must never take the daemon down with it
            job.detail = f"job raised {type(e).__name__}: {e}"
        job.rc = rc
        job.finished_s = time.time()
        self._job_walls.append(job.finished_s - job.started_s)
        job.stderr_tail = job.errbuf.getvalue()[-4000:]
        # a resident daemon must not retain every finished job's full
        # output buffers for its whole life: keep only the served tail
        # and drop the StringIOs (re-pointing the job's drain at the
        # daemon stderr first — a late message must not hit a dropped
        # buffer)
        if job.drain is not None:
            job.drain.stderr = self.stderr
        job.errbuf = job.outbuf = None
        job.stats = self._read_job_stats(job)
        if rc == 0:
            job.state = JOB_DONE
            self.stats.jobs_completed += 1
        elif rc == EXIT_PREEMPTED and job.cancel_requested:
            job.state = JOB_CANCELLED
            job.detail = ("cancelled at a batch boundary; the partial "
                          "report is checkpointed (resumable)")
            self.stats.jobs_cancelled += 1
        elif rc == EXIT_PREEMPTED:
            job.state = JOB_PREEMPTED
            job.detail = ("preempted by service drain; --resume "
                          "completes it")
            self.stats.jobs_preempted += 1
        else:
            job.state = JOB_FAILED
            if not job.detail:
                job.detail = f"exit {rc}"
            self.stats.jobs_failed += 1
        self.stats.rollup_job(job.stats)

    def _read_job_stats(self, job: Job) -> dict | None:
        if job.stats_path is None:
            return None
        try:
            import json
            with open(job.stats_path) as f:
                st = json.load(f)
        except (OSError, ValueError):
            return None
        if job.stats_injected:
            try:
                os.unlink(job.stats_path)
            except OSError:
                pass
        return st if isinstance(st, dict) else None

    # ---- admission -----------------------------------------------------
    def submit(self, argv: list, cwd: str | None = None) -> Job:
        """Validate + admit one job (raises Draining/QueueFull/
        ValueError).  Also the in-process API the tests drive.
        ``cwd`` is the CLIENT's working directory: relative paths in
        the job argv are resolved against it, not the daemon's cwd —
        the cold-to-warm drop-in contract (the client sends it
        automatically)."""
        if not isinstance(argv, list) \
                or not all(isinstance(a, str) for a in argv) \
                or not argv:
            raise ValueError("args must be a non-empty list of strings")
        from pwasm_tpu.cli import _SERVICE_CMDS, _parse_args, CliError
        if argv[0] in _SERVICE_CMDS:
            raise ValueError(
                f"nested service command {argv[0]!r} not allowed")
        if cwd is not None:
            if not isinstance(cwd, str) or not os.path.isabs(cwd):
                raise ValueError("cwd must be an absolute path")
            argv = _absolutize_argv(argv, cwd)
        # parse with the REAL CLI grammar (clustered short flags like
        # `-Do out` included) so the cold-to-warm drop-in contract
        # cannot drift from what cli.run would accept
        try:
            job_opts, _pos = _parse_args(list(argv))
        except CliError as e:
            raise ValueError(f"unparseable job argv: "
                             f"{str(e).splitlines()[-1]}")
        if "o" not in job_opts:
            raise ValueError(
                "service jobs must write their report to a file "
                "(-o <report>): the socket carries control frames, "
                "not report bytes")
        if self.drain.requested:
            raise Draining("service is draining")
        with self._lock:
            self._next_id += 1
            job = Job(id=f"job-{self._next_id:04d}", argv=list(argv))
        job.drain = SignalDrain(stderr=job.errbuf,
                                hard_exit=lambda code: None)
        stats_path = next(
            (a.split("=", 1)[1] for a in argv
             if a.startswith("--stats=")), None)
        if stats_path is None:
            # the daemon needs every job's RunStats for the roll-up
            # and the warm-hit gates: inject a stats sink the client
            # didn't ask for (daemon-owned, deleted after reading)
            stats_path = os.path.join(self._jobdir.name,
                                      f"{job.id}.stats.json")
            job.argv = job.argv + [f"--stats={stats_path}"]
            job.stats_injected = True
        job.stats_path = stats_path
        self.queue.submit(job)     # may raise Draining/QueueFull
        with self._lock:
            self.jobs[job.id] = job
        self.stats.jobs_accepted += 1
        return job

    def _retry_after_s(self) -> float:
        """The queue_full backoff hint: roughly one recent job's wall
        (the deque's maxlen already bounds the window)."""
        walls = list(self._job_walls)
        return round(max(0.5, sum(walls) / len(walls)), 3) if walls \
            else 1.0

    # ---- protocol ------------------------------------------------------
    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        try:
            while True:
                try:
                    req = protocol.read_frame(rfile,
                                              self.max_frame_bytes)
                except protocol.FrameError as e:
                    protocol.write_frame(
                        wfile, protocol.err(e.code, str(e)))
                    if e.fatal:
                        return
                    continue
                if req is None:
                    return
                try:
                    resp = self._dispatch(req)
                except Exception as e:
                    # client-controlled field TYPES can reach stdlib
                    # calls (a string `timeout` into Event.wait, an
                    # unhashable job_id into a dict lookup): a bad
                    # request must cost the CLIENT an error frame,
                    # never the daemon a dead connection thread
                    resp = protocol.err(
                        protocol.ERR_BAD_REQUEST,
                        f"{type(e).__name__}: {e}")
                protocol.write_frame(wfile, resp)
        except (BrokenPipeError, ConnectionResetError, OSError,
                ValueError):
            # the peer went away (possibly mid-result): their problem,
            # never the daemon's — the job keeps running and the next
            # connection can fetch the result
            pass
        finally:
            for f in (rfile, wfile):
                try:
                    f.close()
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "ping":
            return protocol.ok(
                protocol_version=protocol.PROTOCOL_VERSION,
                draining=self._draining)
        if cmd == "submit":
            try:
                job = self.submit(req.get("args"),
                                  cwd=req.get("cwd"))
            except ValueError as e:
                return protocol.err(protocol.ERR_BAD_REQUEST, str(e))
            except Draining as e:
                self.stats.jobs_rejected_draining += 1
                return protocol.err(protocol.ERR_DRAINING, str(e))
            except QueueFull as e:
                # the 429: a well-behaved client backs off and retries
                self.stats.jobs_rejected += 1
                return protocol.err(
                    protocol.ERR_QUEUE_FULL, str(e),
                    queue_depth=self.queue.depth(),
                    max_queue=self.queue.max_queue,
                    retry_after_s=self._retry_after_s())
            return protocol.ok(job_id=job.id,
                               queue_depth=self.queue.depth())
        if cmd == "stats":
            with self._lock:
                running = len(self._running)
            return protocol.ok(stats=self.stats.as_dict(
                queue_depth=self.queue.depth(), running=running,
                draining=self._draining,
                max_queue=self.queue.max_queue,
                max_concurrent=self.max_concurrent))
        if cmd == "drain":
            self.drain.request("drain requested by client")
            self._begin_drain(self.drain.reason)
            with self._lock:
                # snapshot under the lock: a concurrent submit mutates
                # self.jobs, and iterating it bare would raise mid-
                # drain (answering bad_request for a drain that DID
                # latch)
                running = sorted(self._running)
                preempted = sorted(
                    j.id for j in self.jobs.values()
                    if j.state == JOB_PREEMPTED
                    and j.started_s is None)
            return protocol.ok(draining=True, running=running,
                               preempted_queued=preempted)
        if cmd in ("status", "result", "cancel"):
            job = self.jobs.get(req.get("job_id"))
            if job is None:
                return protocol.err(
                    protocol.ERR_UNKNOWN_JOB,
                    f"unknown job_id {req.get('job_id')!r}")
            if cmd == "status":
                return protocol.ok(job=job.describe(),
                                   queue_depth=self.queue.depth())
            if cmd == "result":
                if req.get("wait", True):
                    job.done.wait(req.get("timeout"))
                d = job.describe()
                if job.state not in TERMINAL_STATES:
                    return protocol.ok(job=d, pending=True)
                return protocol.ok(job=d, rc=job.rc, stats=job.stats,
                                   stderr_tail=job.stderr_tail)
            return self._cancel(job)
        return protocol.err(protocol.ERR_UNKNOWN_CMD,
                            f"unknown cmd {cmd!r}")

    def _cancel(self, job: Job) -> dict:
        if job.state == JOB_QUEUED and self.queue.remove(job):
            job.state = JOB_CANCELLED
            job.rc = None
            job.detail = "cancelled while queued (never started)"
            job.finished_s = time.time()
            self.stats.jobs_cancelled += 1
            job.done.set()
            return protocol.ok(state=JOB_CANCELLED, was="queued")
        if job.state in TERMINAL_STATES:
            return protocol.ok(state=job.state, was="terminal")
        # running — or QUEUED-but-already-dequeued (the worker holds
        # it between take() and the RUNNING transition, so the queue
        # removal above missed): a per-job graceful drain either way.
        # The job stops at its next batch boundary with a valid
        # checkpoint — a mid-batch kill would only throw away
        # finished work, and the pre-armed drain flag catches the
        # about-to-run case at its first boundary.
        job.cancel_requested = True
        if job.drain is not None:
            job.drain.request("cancelled by client")
        return protocol.ok(state="cancelling", was="running")


# the argv slots that hold PATHS, resolved against the client's cwd:
# short value flags (from cli._VALUE_FLAGS; -c is clipmax, -d/-p/-m are
# the reference's parsed-but-unread quirks), --long=FILE options, and
# the positional PAF input.
_PATH_SHORT = frozenset("rows")
_PATH_LONG = frozenset(("stats", "profile", "motifs",
                        "ace", "info", "cons"))


def _absolutize_argv(argv: list[str], cwd: str) -> list[str]:
    """Rewrite relative paths in a job argv against the CLIENT's
    ``cwd``, walking tokens with the same grammar as
    ``cli._parse_args`` (clustered short flags, joined or separated
    values, ``--long=value``) so the rewrite cannot disagree with what
    the run will parse.  Unknown flags pass through untouched — the
    submit-time validation rejects the argv right after with the CLI's
    own diagnostic."""
    from pwasm_tpu.cli import _BOOL_FLAGS, _VALUE_FLAGS

    def ab(v: str) -> str:
        # "-" is the conventional stdin marker, not a path
        if not v or v == "-" or os.path.isabs(v):
            return v
        return os.path.join(cwd, v)

    out: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            if "=" in a:
                k, v = a[2:].split("=", 1)
                if k in _PATH_LONG:
                    a = f"--{k}={ab(v)}"
            out.append(a)
        elif a.startswith("-") and len(a) > 1:
            j = 1
            rebuilt = "-"
            value_flag = None      # set when the flag's value is the
            #                        NEXT argv token
            while j < len(a):
                ch = a[j]
                if ch in _BOOL_FLAGS:
                    rebuilt += ch
                    j += 1
                elif ch in _VALUE_FLAGS:
                    rebuilt += ch
                    if j + 1 < len(a):     # joined value: -oFILE
                        v = a[j + 1:]
                        rebuilt += ab(v) if ch in _PATH_SHORT else v
                    else:
                        value_flag = ch
                    j = len(a)
                else:
                    rebuilt = a            # unknown flag: untouched
                    j = len(a)
            out.append(rebuilt)
            if value_flag is not None and i + 1 < len(argv):
                i += 1
                v = argv[i]
                out.append(ab(v) if value_flag in _PATH_SHORT else v)
        else:
            out.append(ab(a))              # positional: the PAF input
        i += 1
    return out


def _socket_alive(path: str) -> bool:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(0.5)
    try:
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


def serve_main(argv: list[str], stdout=None, stderr=None) -> int:
    """The ``pwasm-tpu serve`` entry point."""
    stderr = stderr if stderr is not None else sys.stderr
    opts: dict[str, str] = {}
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            opts[k] = v
        elif a in ("-h", "--help"):
            stderr.write(_SERVE_USAGE)
            return EXIT_USAGE
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid argument: {a}\n")
            return EXIT_USAGE
    sock = opts.pop("socket", None)
    if not sock:
        stderr.write(f"{_SERVE_USAGE}\nError: --socket=PATH is "
                     "required\n")
        return EXIT_USAGE
    nums = {}
    for knob, dflt in (("max-queue", 16), ("max-concurrent", 1),
                       ("max-frame-bytes", protocol.MAX_FRAME_BYTES)):
        val = opts.pop(knob, None)
        if val is None:
            nums[knob] = dflt
        elif val.isascii() and val.isdigit() and int(val) >= 1:
            nums[knob] = int(val)
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid --{knob} value: "
                         f"{val}\n")
            return EXIT_USAGE
    if opts:
        stderr.write(f"{_SERVE_USAGE}\nInvalid argument: "
                     f"--{next(iter(opts))}\n")
        return EXIT_USAGE
    daemon = Daemon(sock, max_queue=nums["max-queue"],
                    max_concurrent=nums["max-concurrent"],
                    max_frame_bytes=nums["max-frame-bytes"],
                    stderr=stderr)
    try:
        return daemon.serve()
    except PwasmError as e:
        stderr.write(str(e))
        return e.exit_code
