"""The warm-pool serving daemon (``pwasm-tpu serve``).

One resident process, one unix socket, a bounded FIFO queue, and a
small worker pool executing jobs through the EXISTING ``cli.run`` path
— so a served job's outputs are byte-identical to a cold CLI run of
the same argv.  What the daemon adds is everything a cold run cannot
amortize:

- **one warm process**: imports, the jit/compile caches, and the
  bounded backend probe are paid once — jobs after the first answer
  the probe from warm state (``backend.warm_hits`` in each job's
  ``--stats``, gated by the bench's ``realistic_serve_warm_jobs``);
- **one resilience stack**: the :class:`WarmContext` carries the
  supervisor's breaker/ceiling state and the single
  ``BackendHealthMonitor`` across jobs — a flap that opens the breaker
  in job N leaves it open for job N+1 (no re-trip, no doomed device
  attempts), and a reclose re-promotes every subsequent job;
- **one drain**: the first SIGTERM/SIGINT (or the ``drain`` protocol
  command) latches admission shut, pulls every running job's drain
  flag (each finishes its in-flight batch, checkpoints, and exits 75
  "preempted, resumable"), marks still-queued jobs preempted without
  starting them, and the daemon itself exits 75.  A second signal
  hard-aborts, exactly like the CLI.

Concurrency model: the accept loop and each client connection run on
their own threads; ``--max-concurrent`` worker threads execute jobs.
Worker threads can never install signal handlers
(``SignalDrain.install`` no-ops off the main thread by design), so the
daemon's OWN drain — installed on the main thread — is the one signal
surface, fanned out to per-job drain flags.
"""

from __future__ import annotations

import os
import re
import socket
import sys
import tempfile
import threading
import time

from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE, PwasmError
from pwasm_tpu.fleet.fencing import EpochLease
from pwasm_tpu.resilience.lifecycle import SignalDrain
from pwasm_tpu.service import protocol
from pwasm_tpu.service.cache import ByteLedger
from pwasm_tpu.service.journal import (JOURNAL_VERSION, REC_ADMIT,
                                       REC_CACHE_HIT, REC_CANCEL,
                                       REC_EVICT, REC_FINISH,
                                       REC_START, JobJournal,
                                       fold_records)
from pwasm_tpu.service.leases import LeaseManager
from pwasm_tpu.service.queue import (JOB_CANCELLED, JOB_DONE, JOB_FAILED,
                                     JOB_PREEMPTED, JOB_QUEUED,
                                     JOB_RUNNING, TERMINAL_STATES,
                                     Draining, Job, JobQueue, QueueFull,
                                     ServiceStats, StreamBook)

_SERVE_USAGE = """Usage:
 pwasm-tpu serve --socket=PATH [--listen=HOST:PORT]
                 [--max-queue=N] [--max-queue-total=N]
                 [--max-concurrent=N] [--priority-lanes=hi,lo]
                 [--devices-per-job=N] [--lanes=N]
                 [--journal=PATH|off] [--journal-dir=DIR]
                 [--spool-threshold-bytes=N]
                 [--spool-dir=DIR] [--stream-buffer=N]
                 [--stream-idle-s=S]
                 [--compile-cache-dir=DIR] [--warmup[=tpu|cpu]]
                 [--max-frame-bytes=N] [--metrics-textfile=PATH]
                 [--log-json=FILE] [--log-json-max-bytes=N]
                 [--trace-json=FILE]
                 [--result-ttl-s=S] [--max-results=N]
                 [--result-cache=DIR|off]
                 [--result-cache-max-bytes=N]
                 [--cache-prefetch[=N]]
                 [--canary-interval=S] [--slo-rules=FILE|off]
                 [--tls-cert=PEM --tls-key=PEM
                  [--tls-client-ca=PEM]]
                 [--auth-tokens=FILE] [--rate-limit=N[/s][:burst]]

   --socket=PATH        unix socket to listen on (required)
   --listen=HOST:PORT   ALSO serve the same protocol over TCP (the
                        fleet transport, docs/FLEET.md; port 0 = any
                        free port).  TCP peers have no SO_PEERCRED,
                        so their fair-share identity is the explicit
                        client_token frame field (`submit
                        --client-token=TOK` buckets as tok:TOK);
                        untokened TCP clients share the anonymous
                        bucket
   --journal-dir=DIR    place the job journal (and, unless --spool-dir
                        says otherwise, the result spool) under DIR as
                        <member-name>.journal instead of next to the
                        socket — point it at shared durable storage
                        and a fleet router (`pwasm-tpu route
                        --journal-dir=DIR`) can read a dead member's
                        journal to fail its jobs over; leave it unset
                        for fast local disk (same-host routers still
                        find <socket>.journal).  docs/FLEET.md
   --compile-cache-dir=DIR  persistent XLA compilation cache (via the
                        jaxcompat shim) for every job this daemon
                        runs: a restarted or newly joined fleet
                        member loads compiled programs from DIR
                        instead of paying lane 1's compile wall again
   --warmup[=tpu|cpu]   ahead-of-time warmup at daemon start (default
                        tpu): a tiny synthetic job runs through the
                        normal supervised path on a free lane,
                        paying the backend probe, the jax import and
                        the pow2-bucket program compiles BEFORE the
                        first real job arrives (and populating
                        --compile-cache-dir when set)
   --max-queue=N        admission control: PER-CLIENT queued-job
                        quota (client = socket-peer uid, or the
                        submit frame's client= field), beyond which
                        that client's submit answers queue_full
                        (default 16); other clients keep their own
                        quota — one heavy submitter cannot eat the
                        whole queue
   --max-queue-total=N  global queued-job backstop across all clients
                        (default 8 x max-queue)
   --priority-lanes=A,B strict priority tiers, highest first: a
                        submit tagged priority=A is always dequeued
                        before one tagged B; untagged submits land in
                        the LOWEST lane.  Fair-share round-robin over
                        clients applies within each lane
   --journal=PATH|off   durable job journal (default: <socket>.journal)
                        — every admission/start/finish is an fsync'd
                        NDJSON record, so a daemon restarted after a
                        hard crash (kill -9, OOM-kill) replays it:
                        queued jobs re-queue, running jobs re-admit as
                        --resume continuations of their own ckpts, and
                        finished results restore.  "off" disables
   --spool-threshold-bytes=N  spool a finished job's result (stats +
                        stderr tail) to disk once its JSON exceeds N
                        bytes: daemon RAM keeps only an index entry,
                        `result` reads stream from the spool file
                        (CRC-verified, fsio-atomic), eviction unlinks
                        it — resident result memory stays bounded
                        regardless of report size (default: off)
   --spool-dir=DIR      where spooled results live (default:
                        <socket>.spool/); setting it enables spooling
                        with a 65536-byte threshold
   --max-concurrent=N   worker threads executing jobs (default 1).
                        Each running job also holds a DEVICE LEASE
                        (one lane of the device inventory), so K
                        concurrent jobs run on K disjoint lanes — a
                        v5e-8 with --max-concurrent=8 runs 8 jobs on
                        8 chips, not 8 jobs interleaved on chip 0
   --devices-per-job=N  devices granted per lease (default 1): a big
                        job leases N chips and its --shard work spans
                        exactly its lane (ICI-sharded batch + psum'd
                        consensus counts over the leased devices)
   --lanes=N            lease-lane count (default: --max-concurrent).
                        Set it to chips/devices-per-job on a real
                        mesh; with lanes < max-concurrent a dequeued
                        job WAITS for a free lease (FIFO, measured by
                        the lease-wait histogram), not just a thread
   --stream-buffer=N    per-stream buffered-record quota (default
                        512): records fed over stream-data frames but
                        not yet consumed by the executing job.  A
                        stream past its quota (or over its fair share
                        of the 4x global ceiling once streams together
                        hit it) answers queue_full — the client backs
                        off and resends (docs/STREAMING.md)
   --stream-idle-s=S    drain a stream job after S seconds with no
                        stream-data and no stream-end (default 300):
                        the job exits 75 with a valid checkpoint —
                        preempted-resumable, never silently complete
                        with missing records — so a vanished client
                        cannot wedge a worker forever
   --max-frame-bytes=N  protocol frame ceiling (default 8 MiB)
   --metrics-textfile=PATH  publish the daemon's Prometheus text
                        exposition here (atomic rewrite after every
                        job) for a node-exporter textfile collector;
                        the same exposition answers the `metrics`
                        protocol command / `pwasm-tpu metrics` verb
   --log-json=FILE      append structured NDJSON service events (job
                        admit/start/finish/evict, drains, breaker
                        transitions inside jobs go to each job's own
                        --log-json); every job event carries the
                        job's trace_id
   --log-json-max-bytes=N  rotate the service event log once it
                        passes N bytes (FILE moves to FILE.1, one
                        generation kept; a log_rotate event opens the
                        fresh file) — a long-lived daemon's log stays
                        bounded
   --trace-json=FILE    record the daemon's job-lifecycle spans
                        (queue wait, lease wait, exec — each stamped
                        with the job's trace_id) as Chrome trace JSON,
                        written at exit; `pwasm-tpu trace-merge` joins
                        it with a client's trace onto one wall-
                        anchored timeline (docs/OBSERVABILITY.md)
   --result-ttl-s=S     evict a finished job's result S seconds after
                        it finished (default: keep forever); evicted
                        job ids answer unknown_job
   --max-results=N      keep at most N finished-job results (least-
                        recently-accessed evicted first)
   --result-cache=DIR   content-addressed result cache
                        (docs/SERVICE.md): a submit whose key —
                        sha256 over (canonicalized ref-FASTA digest,
                        input digest, result-affecting flags, output
                        kinds) — matches a stored entry is answered
                        AT ADMISSION from the cached bytes: zero
                        queue, lease, or device involvement
                        (backend.probes == 0 in its stats), a
                        `cache_hit` journal record for replay truth.
                        Completed jobs insert their outputs; every
                        serve is CRC-verified (rot = miss, never a
                        corrupt byte).  Point a FLEET's members at
                        one shared DIR (the --journal-dir placement
                        idea) and a job answered by ANY member never
                        re-runs anywhere.  Default: off
   --result-cache-max-bytes=N  evict least-recently-used cache
                        entries past N total bytes (the cache_thrash
                        SLO rule pages when a mis-sized budget makes
                        eviction keep pace with insertion)
   --cache-prefetch[=N] before taking traffic, page the N hottest
                        (most-recently-served) --result-cache entries
                        through a CRC-verified read (default N: 64) —
                        a scaler-spawned member joining a shared
                        cache dir serves its first repeat job from a
                        warm cache, like a long-lived sibling
   --canary-interval=S  run a synthetic canary probe every S seconds
                        (service/canary.py): the deterministic warmup
                        corpus through a free lane's normal serving
                        path, byte-verified against a golden digest —
                        pwasm_canary_* metrics feed the canary_failing
                        SLO rule, so a silently-wedged lane fires an
                        alert instead of waiting for a user job
   --slo-rules=FILE|off JSON list of SLO rule objects merged over the
                        default set (obs/catalog.py; a rule with a
                        default's name replaces it) — evaluated
                        continuously by the in-process engine
                        (obs/slo.py) feeding pwasm_alerts_firing and
                        the `health` verb; "off" disables the engine
                        (the self-monitoring A/B knob).  Rule catalog:
                        docs/OBSERVABILITY.md
   --tls-cert=PEM --tls-key=PEM  upgrade the --listen TCP listener to
                        TLS (stdlib ssl, TLS1.2+ floor; the unix
                        socket keeps kernel peer credentials and
                        never wraps).  Handshake failures — plaintext
                        probes, downgrades, bad certs — are counted
                        (pwasm_transport_tls_handshake_failures_total)
                        and answered with a loud close, never a hang
   --tls-client-ca=PEM  require mTLS: client certificates verified
                        against this CA, and the peer certificate's
                        CN becomes the connection's ATTESTED identity
                        (cn:<name>, ranking above client_token in the
                        resolution order; docs/FLEET.md security
                        model)
   --auth-tokens=FILE   scoped capability tokens (service/authz.py):
                        FILE maps principal (token, cn:<name>,
                        uid:<n>, or the "*" default) -> scopes from
                        {submit, read, cancel-own, admin}.  Control-
                        plane verbs (drain, lease-grant, fence) need
                        admin; cancel needs ownership or admin; an
                        unauthorized frame answers `unauthorized`
                        having changed no queue/journal state.  The
                        file hot-reloads on the accept-loop tick
                        (CRC'd, keep-last-good).  Unset = every verb
                        open, byte-identical to the pre-auth daemon
   --rate-limit=N[/s][:burst]  per-identity token bucket in front of
                        admission (submit/stream): past N requests/s
                        (bucket depth `burst`, default max(1,N)) a
                        client's frame answers `overloaded` with a
                        truthful retry_after_s — one hot loop cannot
                        starve admission for everyone else

 SIGTERM/SIGINT (or the `drain` protocol command) drains gracefully:
 in-flight jobs finish at their next batch boundary and checkpoint,
 queued jobs are reported preempted-resumable, new submissions are
 rejected, and the daemon exits 75.  A second signal hard-aborts.
"""


# fair-share client identities double as metric label values and
# journal fields: keep the charset boring (empty = anonymous bucket)
_CLIENT_RE = re.compile(r"^[A-Za-z0-9_.:@/-]*$")


def _num(v, default: float) -> float:
    """A journal field that should be a number, defensively: replay
    must survive bit-rot or hand edits in ANY field, so a wrong-typed
    timestamp/size degrades to the default instead of raising into
    daemon startup."""
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else default


class WarmContext:
    """The state ONE warm process shares across consecutive
    ``cli.run`` invocations.  ``cli.run(..., warm=ctx)`` reads/writes:

    - ``drain``             the SignalDrain the run must honor (the
                            daemon supplies a per-job one via
                            :class:`_JobWarm`);
    - ``monitor`` / ``supervisor_state``  legacy slots for a bare
                            warm context (tests, embedding callers).
                            Under the daemon these now live on the
                            per-lane :class:`DeviceLease` instead
                            (service/leases.py) so a flap on lane 0
                            cannot degrade lane 1 — ``_JobWarm``
                            redirects both to the job's lease;
    - ``host_executor()``   the single persistent host-pipeline worker
                            (report analyze→format stage) shared by
                            consecutive jobs, so the warm path pays no
                            per-job thread spawn and the worker's
                            thread-local ``FormatBuffers`` scratch
                            (report/rowbytes.py) survives job→job.
    """

    def __init__(self) -> None:
        self.drain = None
        self.monitor = None
        self.supervisor_state: dict | None = None
        self.host_pool = None
        self.compile_cache_dir: str | None = None  # persistent XLA
        #   compilation cache dir every job arms before its first
        #   device compile (serve --compile-cache-dir)
        self.result_cache_dir: str | None = None   # content-addressed
        #   result cache dir (serve --result-cache): a served
        #   --many2many job reads it for per-CDS SECTION caching —
        #   the daemon's own whole-job lookup happens at admission
        self.lock = threading.Lock()

    def host_executor(self):
        """The warm process's host report-pipeline worker, created on
        first use and REUSED across jobs (cli._main_loop asks for it
        instead of spawning its own per run).  One single-thread
        executor is correct even with a wider job-worker pool: each
        batch's finish closure joins its own future, so interleaved
        jobs only share the worker's time, never its results."""
        with self.lock:
            if self.host_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self.host_pool = ThreadPoolExecutor(
                    1, thread_name_prefix="pwasm-hostpipe-warm")
            return self.host_pool

    def close(self) -> None:
        """Retire the shared pipeline worker (daemon shutdown)."""
        with self.lock:
            pool, self.host_pool = self.host_pool, None
        if pool is not None:
            pool.shutdown(wait=True)


class _JobWarm:
    """Per-job view of the warm process: this job's own drain flag,
    the shared host-pipeline executor, and — NEW with the device-lease
    scheduler (ISSUE 8) — the LANE's warm state.  The supervisor's
    breaker/ceiling snapshot and the health monitor live on the
    :class:`~pwasm_tpu.service.leases.DeviceLease` the job holds, not
    on the daemon: a flap that opens lane 0's breaker degrades only
    the jobs that later run on lane 0, never lane 1's healthy chip.
    The lease is held exclusively for the job's duration, so the
    monitor ``attach()`` rebinding that made cross-job sharing unsafe
    under a wide worker pool is race-free per lane by construction.

    ``lease_devices`` (a ``(lo, hi)`` device-index span, or None) is
    what ``cli.run`` reads to scope the job's device placement — set
    only when the daemon actually runs multiple lanes or grants more
    than one device, so a classic single-lane daemon behaves exactly
    as before."""

    def __init__(self, shared: WarmContext, drain: SignalDrain,
                 lease, expose_devices: bool = False,
                 trace_id: str | None = None, flight=None):
        self._shared = shared
        self.drain = drain
        self.lease = lease
        self.lease_devices = lease.devices if expose_devices else None
        # cross-process trace identity + the per-job flight record
        # (ISSUE 11): cli.run stamps the trace_id on its event lines
        # (run_id) and feeds its spans into the flight recorder
        self.trace_id = trace_id
        self.flight = flight

    @property
    def compile_cache_dir(self):
        return self._shared.compile_cache_dir

    @property
    def result_cache_dir(self):
        return self._shared.result_cache_dir

    @property
    def monitor(self):
        return self.lease.monitor

    @monitor.setter
    def monitor(self, m) -> None:
        self.lease.monitor = m

    @property
    def supervisor_state(self):
        return self.lease.supervisor_state

    @supervisor_state.setter
    def supervisor_state(self, st) -> None:
        self.lease.supervisor_state = st

    def host_executor(self):
        return self._shared.host_executor()


class Daemon:
    """The serving daemon.  ``runner`` is injectable for tests and
    defaults to ``pwasm_tpu.cli.run``."""

    def __init__(self, socket_path: str, max_queue: int = 16,
                 max_concurrent: int = 1,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 stderr=None, runner=None, metrics_textfile=None,
                 log_json=None, result_ttl_s: float | None = None,
                 max_results: int | None = None,
                 lanes: int | None = None, devices_per_job: int = 1,
                 journal_path: str | None = "auto",
                 max_queue_total: int | None = None,
                 priority_lanes: tuple[str, ...] | None = None,
                 spool_threshold_bytes: int | None = None,
                 spool_dir: str | None = None,
                 stream_buffer: int = 512,
                 stream_idle_s: float | None = 300.0,
                 log_json_max_bytes: int | None = None,
                 trace_json: str | None = None,
                 listen: str | None = None,
                 journal_dir: str | None = None,
                 compile_cache_dir: str | None = None,
                 warmup: str | None = None,
                 canary_interval_s: float | None = None,
                 slo_rules=None,
                 result_cache: str | None = None,
                 result_cache_max_bytes: int | None = None,
                 result_cache_ttl_s: float | None = None,
                 cache_prefetch: int | None = None,
                 tls=None, auth_tokens: str | None = None,
                 rate_limit: tuple | None = None):
        self.socket_path = socket_path
        # fleet transport (docs/FLEET.md): an optional TCP listener
        # joining the unix socket — same protocol, token-based client
        # identity (no SO_PEERCRED on AF_INET)
        self.listen = listen
        self.tcp_port: int | None = None   # actual port after bind
        self.warmup = warmup
        self._t0_mono = time.monotonic()   # uptime origin (the lane
        #   busy-fraction gauges divide by it)
        self.max_concurrent = max(1, int(max_concurrent))
        # device-lease scheduler (ISSUE 8): every running job holds one
        # lane of the device inventory.  lanes defaults to the worker
        # count (each worker always finds a lease, wait ~0); an
        # explicit --lanes below the worker count makes admission
        # genuinely lease-gated — a dequeued job waits (FIFO) for a
        # free lane, measured by the lease-wait histogram.
        self.devices_per_job = max(1, int(devices_per_job))
        self.leases = LeaseManager(
            lanes if lanes is not None else self.max_concurrent,
            self.devices_per_job)
        # expose the lane's device span to jobs only when the operator
        # actually asked for multi-lane/multi-device serving — a
        # classic 1-lane daemon must behave byte-and-counter
        # identically to PR 5
        self._expose_devices = (self.leases.n_lanes > 1
                                or self.devices_per_job > 1)
        self.max_frame_bytes = int(max_frame_bytes)
        self.stderr = stderr if stderr is not None else sys.stderr
        self._runner = runner
        self.queue = JobQueue(max_queue, max_total=max_queue_total,
                              priority_lanes=priority_lanes)
        # ---- crash safety (ISSUE 9): the durable job journal.  Every
        # admission/start/finish/cancel/evict is an fsync'd NDJSON
        # record (service/journal.py), replayed at the next start on
        # this socket so a kill -9 loses no acked job.
        # --journal-dir is the fleet placement-policy knob: shared
        # durable storage a router can read after this process dies
        # vs the fast-local-disk default next to the socket.  The path
        # arithmetic lives in fleet/transport.py so `serve` and
        # `route` cannot disagree about where a member journals.
        if journal_path == "auto":
            from pwasm_tpu.fleet.transport import member_journal_path
            journal_path = member_journal_path(socket_path,
                                               journal_dir)
        self.journal = JobJournal(journal_path) if journal_path \
            else None
        self._journal_warned = False
        # ---- disk-spooled results (ISSUE 9): past the threshold a
        # finished job's stats/stderr move to <spool_dir>/<id>.result
        # (fsio-atomic, CRC'd like ckpt v2) and RAM keeps an index row
        if spool_dir is None and journal_dir is not None:
            # one placement knob moves BOTH durable surfaces: spool
            # files ride journal finish records, so a router serving a
            # dead member's results needs them on the same storage
            from pwasm_tpu.fleet.transport import target_name
            spool_dir = os.path.join(
                journal_dir, target_name(socket_path) + ".spool")
        if spool_dir is not None and spool_threshold_bytes is None:
            spool_threshold_bytes = 65536
        self.spool_threshold_bytes = spool_threshold_bytes
        self.spool_dir = spool_dir if spool_dir is not None \
            else socket_path + ".spool"
        # ENOSPC degradation (ISSUE 18 satellite): a full disk under
        # the spool or cache dir degrades to pass-through — results
        # are still served from RAM / outputs still written, only the
        # spool/insert is skipped.  Warn ONCE per condition (a busy
        # daemon must not log one line per job for the same full
        # disk); the counters carry the ongoing truth.
        self._spool_warned = False
        self._cache_insert_warned = False
        # persistent XLA compilation cache (ROADMAP item 2b): carried
        # on the warm context so every job's device path arms it (via
        # the jaxcompat shim) before its first compile
        self.compile_cache_dir = compile_cache_dir
        # ---- unified byte ledger (ISSUE 15 satellite): spool AND
        # result-cache byte accounting share ONE lock-guarded ledger,
        # so the two gauges are read from one synchronized source and
        # cannot drift from disk under concurrent evictions
        self.ledger = ByteLedger()
        # ---- streaming ingestion (ISSUE 10): per-stream buffer
        # quotas + fair-share arbitration; stream jobs are otherwise
        # ordinary queue citizens (DRR over clients, leases, journal)
        self.streams = StreamBook(stream_buffer)
        self.stream_idle_s = stream_idle_s
        self._client_lanes: dict[str, int] = {}   # lane a client's
        #   last stream ran on — consecutive/re-opened streams prefer
        #   it, so they inherit the lane's warm breaker/compile state
        self.jobs: dict[str, Job] = {}
        self.stats = ServiceStats()
        self.warm = WarmContext()
        self.warm.compile_cache_dir = compile_cache_dir
        self.drain = SignalDrain(stderr=self.stderr)
        # ---- epoch-lease fencing (ISSUE 16, fleet/fencing.py): when
        # a fleet router governs this member, its stats polls carry a
        # lease {epoch, ttl_s}; missing heartbeats past the TTL means
        # the fleet may have failed our jobs over — self-fence (drain
        # in-flight to checkpoints, refuse new frames) rather than
        # keep writing as a zombie.  Ungoverned daemons never fence.
        self.epoch_lease = EpochLease()
        self._lock = threading.Lock()
        self._running: dict[str, Job] = {}
        # retired m2m-stream flow counters (surveillance sessions,
        # ISSUE 20): live sessions are read off their feeds, finished
        # ones fold here so svc-stats "m2m" stays cumulative
        self._m2m_done = {"sessions": 0, "targets_in": 0,
                          "targets_scored": 0, "targets_reused": 0,
                          "pairs_dispatched": 0, "pairs_reused": 0,
                          "batches": 0, "sections_emitted": 0}
        self._draining = False
        self._closing = threading.Event()
        self._next_id = 0
        self._jobdir: tempfile.TemporaryDirectory | None = None
        from collections import deque
        self._job_walls: deque = deque(maxlen=8)  # recent finished-job
        #                       walls (the retry_after_s hint) — only
        #                       the recent window matters, so bounded
        # ---- observability (ISSUE 6): ONE metrics registry for the
        # daemon's life — queue/admission gauges + job histograms
        # (obs/catalog.py build_service_metrics) plus the cumulative
        # run-level families every finished job's --stats JSON is
        # folded into (fold_run_stats), exposed over the `metrics`
        # protocol command and, optionally, a node-exporter textfile.
        from pwasm_tpu.obs import (EventLog, MetricsRegistry,
                                   Observability, TraceRecorder)
        from pwasm_tpu.obs.catalog import (build_cache_metrics,
                                           build_m2m_metrics,
                                           build_run_metrics,
                                           build_service_metrics,
                                           build_stream_metrics)
        self.registry = MetricsRegistry()
        self.svc_metrics = build_service_metrics(self.registry)
        self.stream_metrics = build_stream_metrics(self.registry)
        self.m2m_metrics = build_m2m_metrics(self.registry)
        self.cache_metrics = build_cache_metrics(self.registry)
        # ---- content-addressed result cache (ISSUE 15): lookup at
        # admission, insert at job finish — the repeat-traffic fast
        # path.  An unusable dir degrades to caching OFF with a
        # warning, never a dead daemon.
        self.cache = None
        if result_cache and result_cache != "off":
            from pwasm_tpu.service.cache import CacheStore
            try:
                self.cache = CacheStore(
                    result_cache, max_bytes=result_cache_max_bytes,
                    ttl_s=result_cache_ttl_s,
                    metrics=self.cache_metrics, ledger=self.ledger)
            except OSError as e:
                self._say(f"warning: --result-cache dir "
                          f"{result_cache} unusable ({e}); result "
                          "caching disabled")
        self.warm.result_cache_dir = result_cache \
            if self.cache is not None else None
        self.cache_prefetch = cache_prefetch   # warm N hottest shared
        #   entries before the socket exists (serve --cache-prefetch)
        self._cache_evict_at = 0.0    # next TTL/budget sweep (mono)
        # foldable counters only: the live run instruments (attempt
        # histogram, run breaker gauge) belong to each run's own obs
        # bundle — the daemon's breaker view is the
        # pwasm_service_breaker_state gauge
        self.run_metrics = build_run_metrics(self.registry,
                                             include_live=False)
        # ---- zero-trust edge (ISSUE 19): TLS on the TCP listener
        # (handshake per-connection, in that connection's thread),
        # scoped capability tokens, per-identity rate limiting.  All
        # three are strictly opt-in — unarmed, every frame and output
        # stays byte-identical to the open daemon.
        from pwasm_tpu.obs.catalog import build_transport_metrics
        self.transport_metrics = build_transport_metrics(self.registry)
        self.tls = tls                     # transport.ServerTLS | None
        self.auth = None
        self._penalty = None
        if auth_tokens:
            from pwasm_tpu.service.authz import (AuthRegistry,
                                                 PenaltyBox)
            # startup is fail-fast (ValueError propagates to the CLI
            # as a usage error): a daemon must never come up OPEN
            # because its token file was bad
            self.auth = AuthRegistry(auth_tokens, say=self._say)
            self._penalty = PenaltyBox()
        self._auth_labels: set[str] = set()   # bounded label universe
        #   for the per-client auth-failure counter (overflow -> other)
        self.rate_limiter = None
        if rate_limit is not None:
            from pwasm_tpu.service.queue import RateLimiter
            self.rate_limiter = RateLimiter(rate_limit[0],
                                            rate_limit[1])
        self.svc_metrics["max_queue"].set(self.queue.max_queue)
        self.svc_metrics["max_concurrent"].set(self.max_concurrent)
        self.svc_metrics["lanes"].set(self.leases.n_lanes)
        self._clients_seen: set[str] = set()   # label universe for the
        #   per-client depth gauge (a drained client reads 0, not gone)
        self.metrics_textfile = metrics_textfile
        self._textfile_lock = threading.Lock()  # fsio's tmp name is
        #   pid-unique, not thread-unique: two workers finishing at
        #   once must not interleave on the same tmp file
        events = None
        if log_json:
            # append (documented): a restarted daemon extends the
            # incident timeline instead of wiping the previous one;
            # --log-json-max-bytes rotates it (FILE -> FILE.1) so a
            # long-lived daemon's log stays bounded
            events = EventLog(path=log_json,
                              max_bytes=log_json_max_bytes)
        # --trace-json (ISSUE 11): the daemon's OWN span recorder —
        # per-job queue-wait/lease-wait/exec spans stamped with the
        # job's trace_id, wall-anchored so `trace-merge` can join them
        # with the submitting client's trace on one timeline
        tracer = TraceRecorder() if trace_json else None
        if tracer is not None:
            dropped = self.run_metrics.get("trace_dropped")
            if dropped is not None:
                tracer.on_drop = lambda c=dropped: c.inc()
        self.obs = Observability(registry=self.registry,
                                 events=events, tracer=tracer,
                                 trace_path=trace_json)
        self.drain.obs = self.obs   # SIGTERM/drain lands in the log
        self.log_json_path = log_json   # the `logs` verb reads it
        # ---- self-monitoring (ISSUE 14): the SLO engine over THIS
        # registry (default rules + user --slo-rules merged by name;
        # slo_rules="off" runs an empty engine — the A/B knob the
        # selfmon-overhead bench leg flips) and the synthetic canary.
        from pwasm_tpu.obs.catalog import (build_canary_metrics,
                                           build_slo_metrics,
                                           default_slo_rules)
        from pwasm_tpu.obs.slo import SloEngine, merge_rules
        self.slo_metrics = build_slo_metrics(self.registry)
        self.canary_metrics = build_canary_metrics(self.registry)
        if slo_rules == "off":
            rules = []
        else:
            rules = merge_rules(default_slo_rules(), slo_rules)
        # evaluate fast enough that a canary failure fires within the
        # detection contract (two canary intervals), slow enough to
        # stay invisible next to the 0.2s accept tick
        eval_s = 1.0
        if canary_interval_s is not None:
            eval_s = min(eval_s, max(0.05, canary_interval_s / 2))
        self.slo = SloEngine(self.registry, rules,
                             metrics=self.slo_metrics,
                             on_event=self.obs.event,
                             eval_interval_s=eval_s)
        self.canary = None
        if canary_interval_s is not None:
            from pwasm_tpu.service.canary import CanaryRunner
            self.canary = CanaryRunner(self, canary_interval_s,
                                       self.canary_metrics)
        # ---- result eviction (the PR 5 "results live forever" gap):
        # TTL and/or LRU ceiling over TERMINAL jobs only — running and
        # queued jobs are never touched; an evicted id answers
        # unknown_job exactly like one that never existed
        self.result_ttl_s = result_ttl_s
        self.max_results = max_results

    # ---- lifecycle -----------------------------------------------------
    def serve(self) -> int:
        """Bind, accept, and run until drained.  Returns the process
        exit code: 75 after a graceful drain (the daemon's own
        "preempted, resumable" — queued jobs were reported resumable),
        matching the per-job contract."""
        if self._runner is None:
            from pwasm_tpu.cli import run as cli_run
            self._runner = cli_run
        if self.cache is not None and self.cache_prefetch:
            # warm-spawn cache replication (ISSUE 17c): page the
            # hottest shared-dir entries through a CRC-verified read
            # BEFORE the socket exists — socket readiness then implies
            # a warm cache, so a scaler-spawned member's first repeat
            # job is an admission hit, not a cold-disk walk
            warmed = self.cache.prefetch(self.cache_prefetch)
            self._say(f"result-cache prefetch: warmed {warmed} "
                      f"entr{'y' if warmed == 1 else 'ies'} from "
                      f"{self.cache.root}")
            self.obs.event("cache_prefetch", warmed=warmed)
        from pwasm_tpu.fleet.transport import (make_unix_listener,
                                               socket_alive)
        if os.path.exists(self.socket_path):
            # a stale socket from a dead daemon: binding over it
            # needs the unlink; a LIVE daemon still holds the
            # listener, so connecting first tells the two apart
            if socket_alive(self.socket_path):
                raise PwasmError(
                    f"Error: a daemon is already serving on "
                    f"{self.socket_path}\n")
        try:
            # the listener factory chmods the socket 0600 (only the
            # serving uid connects by default; TCP is the opt-in
            # wider audience, with TLS/auth as ITS gate)
            sock = make_unix_listener(self.socket_path)
        except OSError as e:
            raise PwasmError(
                f"Error: cannot bind service socket "
                f"{self.socket_path}: {e}\n")
        listeners: list[socket.socket] = [sock]
        if self.listen:
            # the TCP transport (fleet federation): same protocol,
            # same dispatch — only the peer-identity source differs
            from pwasm_tpu.fleet.transport import make_tcp_listener
            try:
                tsock = make_tcp_listener(self.listen)
            except (OSError, ValueError) as e:
                sock.close()
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass
                raise PwasmError(
                    f"Error: cannot bind --listen={self.listen}: "
                    f"{e}\n")
            self.tcp_port = tsock.getsockname()[1]
            listeners.append(tsock)
        import selectors
        sel = selectors.DefaultSelector()
        for l in listeners:
            l.setblocking(False)
            sel.register(l, selectors.EVENT_READ)
        self._jobdir = tempfile.TemporaryDirectory(prefix="pwasm_svc_")
        if self.journal is not None:
            # replay BEFORE workers start and BEFORE the first accept:
            # recovered jobs must be queued when the first worker looks
            # and restored results visible to the first client request
            try:
                self._replay_journal()
                self.journal.open()
            except OSError as e:
                self._say(f"warning: job journal {self.journal.path} "
                          f"unavailable ({e}); serving WITHOUT crash "
                          "recovery")
                self.journal = None
            except Exception as e:
                # a corrupt journal must degrade, never wedge every
                # restart on this socket (the exact path the journal
                # exists to protect): quarantine it ckpt-v2 style and
                # keep journaling on a fresh file
                self.journal.close()
                bad = self.journal.path + ".bad"
                try:
                    from pwasm_tpu.utils.fsio import replace_durable
                    replace_durable(self.journal.path, bad)
                except OSError:
                    bad = "(could not quarantine)"
                self._say(f"warning: job journal replay failed "
                          f"({type(e).__name__}: {e}); journal "
                          f"quarantined to {bad} — any jobs it "
                          "named are NOT recovered (resubmit them), "
                          "new jobs are journaled afresh")
                self.obs.event("journal_quarantined", detail=str(e))
                try:
                    self.journal.open()
                except OSError:
                    self.journal = None
        workers = [threading.Thread(target=self._worker, daemon=True,
                                    name=f"pwasm-svc-worker-{i}")
                   for i in range(self.max_concurrent)]
        drained_at: float | None = None
        with self.drain:     # signal handlers (main thread only)
            for w in workers:
                w.start()
            self._say(f"serving on {self.socket_path}"
                      + (f" + tcp {self.listen.rsplit(':', 1)[0]}:"
                         f"{self.tcp_port}" if self.listen else "")
                      + f" (max-queue {self.queue.max_queue}, "
                      f"max-concurrent {self.max_concurrent}, "
                      f"lanes {self.leases.n_lanes}"
                      + (f" x {self.devices_per_job} device(s)"
                         if self.devices_per_job > 1 else "") + ")")
            self.obs.event("daemon_start", socket=self.socket_path,
                           max_queue=self.queue.max_queue,
                           max_concurrent=self.max_concurrent,
                           lanes=self.leases.n_lanes,
                           devices_per_job=self.devices_per_job)
            self._write_textfile()   # scrapers see a file immediately
            if self.warmup:
                # ahead-of-time shape warmup (ROADMAP item 2b): a tiny
                # synthetic job through the NORMAL supervised path on a
                # free lane, so the backend probe + jax import + the
                # pow2-bucket compiles are paid before the first real
                # job — in the background, admission is already open
                threading.Thread(target=self._run_warmup, daemon=True,
                                 name="pwasm-svc-warmup").start()
            if self.canary is not None:
                # the synthetic canary loop (ISSUE 14): started after
                # _jobdir exists — the probe corpus lives under it
                self.canary.start()
            try:
                while True:
                    self._evict_results()
                    self._selfmon_tick()
                    if self.auth is not None:
                        # token rotation without a restart: the file
                        # hot-reloads on this tick (keep-last-good)
                        self.auth.maybe_reload()
                    if self.epoch_lease.expired():
                        self._fence("lease TTL expired: heartbeats "
                                    "from the fleet router stopped")
                    if self.cache is not None and \
                            time.monotonic() >= self._cache_evict_at:
                        # periodic TTL/budget sweep (cheap no-op when
                        # neither is configured) — an idle cache must
                        # still expire, not only on inserts
                        self._cache_evict_at = time.monotonic() + 5.0
                        self.cache.evict_now()
                    if self.drain.requested:
                        self._begin_drain(self.drain.reason
                                          or "drain requested")
                        if self._drained():
                            # linger briefly so waiters blocked in
                            # `result` get their final frames before
                            # the process goes away
                            if drained_at is None:
                                drained_at = time.monotonic()
                            elif time.monotonic() - drained_at > 0.5:
                                break
                    try:
                        events = sel.select(0.2)
                    except OSError:
                        break
                    if not events:
                        continue
                    for key, _mask in events:
                        try:
                            conn, _ = key.fileobj.accept()
                        except OSError:
                            continue
                        conn.setblocking(True)
                        t = threading.Thread(
                            target=self._handle_conn,
                            args=(conn,), daemon=True)
                        t.start()
            finally:
                self._closing.set()
                for w in workers:
                    w.join(timeout=5.0)
                self.warm.close()
                sel.close()
                for l in listeners:
                    l.close()
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass
                if self._jobdir is not None:
                    self._jobdir.cleanup()
        rc = EXIT_PREEMPTED if self.drain.requested else 0
        if self.drain.requested:
            # CLEAN exit: every admitted job reached a terminal state
            # its client was told about (in-flight drained resumable,
            # queued reported preempted), so there is nothing for a
            # restart to recover — retire the journal and the spool.
            # A hard crash never reaches this line, which is the point.
            if self.journal is not None:
                self.journal.unlink()
            with self._lock:
                spooled = [j for j in self.jobs.values()
                           if j.spool is not None]
            for j in spooled:
                self._unlink_spool(j)
        elif self.journal is not None:
            self.journal.close()
        self.obs.event("daemon_exit", rc=rc,
                       drained=self.drain.requested)
        self._write_textfile()       # final snapshot for the scraper
        if self.obs.tracer is not None and self.obs.trace_path:
            try:
                self.obs.tracer.write(self.obs.trace_path)
                self._say("trace written to "
                          f"{self.obs.trace_path}")
            except OSError as e:
                self._say(f"warning: cannot write --trace-json "
                          f"{self.obs.trace_path}: {e}")
        if self.obs.events is not None:
            self.obs.events.close()
        if self.drain.requested:
            self._say(f"drained — exiting resumable "
                      f"(exit {EXIT_PREEMPTED}); resubmit preempted "
                      "jobs with --resume to complete them")
            return EXIT_PREEMPTED
        return 0

    def _say(self, msg: str) -> None:
        print(f"pwasm: {msg}", file=self.stderr)

    # ---- observability -------------------------------------------------
    def _refresh_gauges(self) -> None:
        """Stamp the point-in-time gauges from the live state.  Called
        before every exposition/stats read and after every job, so the
        Prometheus surface and svc-stats both read the SAME registry
        (they cannot drift — the svc-stats satellite contract)."""
        m = self.svc_metrics
        m["queue_depth"].set(self.queue.depth())
        with self._lock:
            running = len(self._running)
            held = sum(1 for j in self.jobs.values()
                       if j.state in TERMINAL_STATES)
            clients_seen = set(self._clients_seen)   # snapshot: a
            #   concurrent admit's .add() must not resize the set
            #   mid-iteration below
        m["inflight"].set(running)
        m["draining"].set(1 if self._draining else 0)
        m["results_held"].set(held)
        # the daemon-level breaker gauge is the WORST lane (one number
        # for "is anything degraded"); the per-lane vector carries the
        # which
        m["breaker_state"].set(self.leases.breaker_rollup())
        m["lanes_busy"].set(self.leases.busy_count())
        m["lease_waiting"].set(self.leases.waiting_count())
        uptime = max(1e-9, time.monotonic() - self._t0_mono)
        for row in self.leases.lane_states():
            m["lane_breaker_state"].set(row["breaker_state"],
                                        lane=str(row["lane"]))
            # utilization accounting (ISSUE 11): fraction of the
            # daemon's uptime this lane spent leased to a job
            m["lane_busy_fraction"].set(
                round(min(1.0, row["busy_s"] / uptime), 6),
                lane=str(row["lane"]))
        # both byte gauges read the ONE ledger (never a bare int a
        # concurrent eviction could tear)
        m["spool_bytes"].set(self.ledger.value("spool"))
        m["fenced"].set(1 if self.epoch_lease.fenced else 0)
        m["member_epoch"].set(self.epoch_lease.epoch)
        self.cache_metrics["bytes"].set(self.ledger.value("cache"))
        for c, lag in self.streams.client_lag().items():
            self.stream_metrics["lag"].set(lag,
                                           client=c or "default")
        for c, age in self.streams.client_lag_age().items():
            self.stream_metrics["lag_age"].set(round(age, 3),
                                               client=c or "default")
        mm = self._m2m_stats()
        g = self.m2m_metrics
        g["active"].set(mm.get("active", 0))
        with self._lock:
            done_in = self._m2m_done["targets_in"]
        g["live_targets"].set(max(0, mm["targets_in"] - done_in))
        pairs = mm["pairs_dispatched"] + mm["pairs_reused"]
        g["reuse_ratio"].set(
            round(mm["pairs_reused"] / pairs, 6) if pairs else 0.0)
        depths = self.queue.client_depths()
        for c in clients_seen | set(depths):
            # every client ever admitted keeps a series: a drained
            # client reads 0 (a disappearing series looks like a
            # scrape gap, not an emptied queue)
            m["client_queue_depth"].set(depths.get(c, 0),
                                        client=c or "default")

    def _selfmon_tick(self) -> None:
        """One accept-loop tick of the SLO engine (ISSUE 14): refresh
        the gauges the rules read, then evaluate — time-gated inside
        the engine so the 0.2s accept cadence costs nothing between
        evaluation intervals."""
        if self.slo.due():
            self._refresh_gauges()
            self.slo.evaluate()

    def _health(self) -> dict:
        """The `health` verb body: a FRESH evaluation (a probe must
        see now, not the last timer tick), the verdict + firing rules,
        and the canary roll-up."""
        self._refresh_gauges()
        h = self.slo.evaluate()
        h["canary"] = self.canary.summary() \
            if self.canary is not None else None
        return h

    def _write_textfile(self) -> None:
        """Atomic textfile publish (fsync-then-replace via
        ``utils.fsio``) — best-effort: a full disk costs a warning,
        never the serving loop."""
        if not self.metrics_textfile:
            return
        try:
            with self._textfile_lock:
                self._refresh_gauges()
                self.registry.write_textfile(self.metrics_textfile)
        except OSError as e:
            self._say(f"warning: cannot write --metrics-textfile "
                      f"{self.metrics_textfile}: {e}")

    # ---- crash safety: journal + spool (ISSUE 9) -----------------------
    def _journal_append(self, rec: str, **fields) -> None:
        """Durably journal one job transition.  A failed append warns
        ONCE and latches (the daemon keeps serving without crash
        recovery — a full disk must not take the fleet down), never
        raises into the serving path."""
        if self.journal is None:
            return
        if self.journal.append(rec, t=round(time.time(), 3),
                               **fields):
            self.svc_metrics["journal_records"].inc(rec=rec)
        elif not self._journal_warned:
            self._journal_warned = True
            self._say(f"warning: job-journal append failed "
                      f"({self.journal.broken}); continuing WITHOUT "
                      "crash recovery")
            self.obs.event("journal_broken",
                           detail=self.journal.broken)

    def _replay_journal(self) -> None:
        """Rebuild the job table from the journal a crashed
        predecessor left behind (serve() calls this before the first
        accept).  Per admitted job, in admission order:

        - ``finish`` record → restored as a terminal result-index
          entry (stats stream from its spool file when it had one);
        - ``cancel`` without ``finish`` → terminal ``cancelled`` (the
          cancel was acked; silently re-running would un-cancel it);
        - ``start`` without ``finish`` → the crash killed it mid-run:
          re-admitted as a ``--resume`` continuation of its own report
          checkpoint, with lane affinity for the lane it ran on — the
          ckpt-v2 resume contract makes the recovered report
          byte-identical to a never-crashed run;
        - bare ``admit`` → re-queued exactly as submitted.

        Afterwards the journal is compacted to the surviving records
        so restart cost tracks live state, not daemon history."""
        records = self.journal.replay()
        folded = fold_records(records) if records else {}
        if not folded:
            return
        rows = sorted(folded.items(), key=lambda kv: kv[1]["_ord"])
        keep: list[dict] = []
        n_requeued = n_resumed = n_restored = 0
        max_num = 0
        for jid, row in rows:
            try:
                max_num = max(max_num, int(jid.rsplit("-", 1)[-1]))
            except ValueError:
                pass
            if row["evicted"]:
                continue
            admit = row["admit"]
            argv = admit.get("argv")
            if not isinstance(argv, list) \
                    or not all(isinstance(a, str) for a in argv):
                continue
            client = str(admit.get("client") or "")
            priority = str(admit.get("priority") or "")
            trace_id = str(admit.get("trace_id") or "")
            fin = row["finish"]
            if fin is not None or row["cancel"] is not None:
                job = Job(id=jid, argv=list(argv), client=client,
                          priority=priority, trace_id=trace_id)
                job.submitted_s = _num(admit.get("t"),
                                       job.submitted_s)
                if fin is not None:
                    job.state = fin.get("state") \
                        if fin.get("state") in TERMINAL_STATES \
                        else JOB_FAILED
                    job.rc = fin.get("rc") \
                        if isinstance(fin.get("rc"), int) else None
                    job.detail = str(fin.get("detail") or "")
                    job.finished_s = _num(fin.get("t"), time.time())
                    spool = fin.get("spool")
                    if isinstance(spool, dict) \
                            and isinstance(spool.get("path"), str):
                        if os.path.exists(spool["path"]):
                            job.spool = {
                                "path": spool["path"],
                                "bytes": int(_num(
                                    spool.get("bytes"), 0))}
                            self.ledger.add("spool",
                                            job.spool["bytes"])
                        else:
                            job.detail += \
                                " [spooled result lost in crash]"
                else:
                    # a cancel the crash interrupted: the client was
                    # told "cancelling", so re-running would UN-cancel
                    # it — land terminal, resumable by resubmission
                    job.state = JOB_CANCELLED
                    job.detail = ("cancel was in flight when the "
                                  "daemon crashed; not re-run — "
                                  "resubmit (with --resume if a "
                                  "checkpoint exists) to complete it")
                    job.finished_s = time.time()
                job.done.set()
                self.jobs[jid] = job
                keep.append(dict(admit))
                fin_rec = {"v": JOURNAL_VERSION, "rec": REC_FINISH,
                           "job_id": jid,
                           "state": job.state, "rc": job.rc,
                           "detail": job.detail or None,
                           "spool": job.spool,
                           "t": round(job.finished_s, 3)}
                keep.append(fin_rec)
                n_restored += 1
                continue
            if admit.get("stream"):
                # a live-at-crash SOCKET stream: its records came over
                # a connection the crash severed, so the daemon cannot
                # re-run it alone — land it terminal
                # preempted-RESUMABLE (records up to the last
                # batch-boundary ckpt are durable; the client re-opens
                # a stream with --resume and re-sends, byte-identical
                # by the resume contract), and remember its lane so
                # the re-opened stream inherits the warm state
                job = Job(id=jid, argv=list(argv), client=client,
                          priority=priority, trace_id=trace_id)
                job.stream = True
                job.submitted_s = _num(admit.get("t"),
                                       job.submitted_s)
                job.state = JOB_PREEMPTED
                job.rc = EXIT_PREEMPTED
                job.detail = (
                    "stream interrupted by a daemon crash; records "
                    "up to the last checkpoint are durable — re-open "
                    "the stream with --resume and re-send the "
                    "records to complete it")
                job.finished_s = time.time()
                job.done.set()
                self.jobs[jid] = job
                start = row["start"]
                if start is not None \
                        and isinstance(start.get("lane"), int):
                    self._client_lanes.setdefault(client,
                                                  start["lane"])
                keep.append(dict(admit))
                keep.append({"v": JOURNAL_VERSION, "rec": REC_FINISH,
                             "job_id": jid, "state": JOB_PREEMPTED,
                             "rc": EXIT_PREEMPTED,
                             "detail": job.detail,
                             "t": round(job.finished_s, 3)})
                self.stats.jobs_preempted += 1
                n_restored += 1
                continue
            # live at crash time: re-queue, resuming if it had started
            resume = row["start"] is not None
            run_argv = list(argv)
            if resume and "--resume" not in run_argv:
                run_argv.append("--resume")
            job = Job(id=jid, argv=list(run_argv), client=client,
                      priority=priority, trace_id=trace_id)
            job.recovered = True
            job.submitted_s = _num(admit.get("t"), job.submitted_s)
            if resume and isinstance(row["start"].get("lane"), int):
                job.prefer_lane = row["start"]["lane"]
            job.detail = ("recovered from the job journal "
                          + ("(daemon crashed mid-run); resuming "
                             "from its checkpoint" if resume
                             else "(daemon crashed while it was "
                             "queued); re-queued"))
            self._arm_job(job)
            try:
                self.queue.submit(job)
            except (Draining, QueueFull) as e:
                # only reachable when queue limits SHRANK across the
                # restart: surface it as a failed job, never a lost one
                job.state = JOB_FAILED
                job.detail = ("journal recovery could not re-queue "
                              f"({e})")
                job.finished_s = time.time()
                job.done.set()
                self.jobs[jid] = job
                continue
            self.jobs[jid] = job
            self._clients_seen.add(client)
            new_admit = dict(admit)
            new_admit.update({"v": JOURNAL_VERSION, "rec": REC_ADMIT,
                              "job_id": jid, "argv": run_argv,
                              "client": client,
                              "priority": priority})
            keep.append(new_admit)
            self.stats.jobs_recovered += 1
            if resume:
                n_resumed += 1
            else:
                n_requeued += 1
        with self._lock:
            self._next_id = max(self._next_id, max_num)
        self.journal.compact(keep)
        self.stats.journal_replays += 1
        self.svc_metrics["journal_replays"].inc()
        self.obs.event("journal_replay", requeued=n_requeued,
                       resumed=n_resumed, restored=n_restored)
        self._say(f"journal replay: {n_requeued} queued job(s) "
                  f"re-queued, {n_resumed} interrupted job(s) "
                  f"re-admitted with --resume, {n_restored} "
                  "terminal result(s) restored")

    def _spool_result(self, job: Job) -> None:
        """Move a finished job's RAM-resident result (its RunStats
        JSON + stderr tail) to the spool dir once the serialized form
        passes ``--spool-threshold-bytes``: the daemon keeps only the
        index row (path + size), so resident result memory is bounded
        no matter how large reports grow.  The file is published via
        the audited fsync-then-replace and CRC'd like ckpt v2 — a torn
        or rotted spool is detected at read time, never served."""
        if self.spool_threshold_bytes is None or job.spool is not None:
            return
        import json

        from pwasm_tpu.utils.fsio import (ensure_private_dir,
                                          payload_crc,
                                          write_durable_text)
        flight = None
        if job.flight is not None:
            # the flight record is finalized HERE (phase walls are all
            # in by the terminal state) and rides the spool payload —
            # `inspect` on a spooled job reads it back CRC-verified
            wall = (job.finished_s or time.time()) - job.submitted_s
            flight = job.flight.summary(wall_s=wall)
        payload = {"version": 1, "job_id": job.id,
                   "state": job.state, "rc": job.rc,
                   "trace_id": job.trace_id or None,
                   "flight": flight,
                   "stats": job.stats,
                   "stderr_tail": job.stderr_tail}
        blob = json.dumps(payload, sort_keys=True,
                          separators=(",", ":"))
        if len(blob) < self.spool_threshold_bytes:
            return
        payload["crc"] = payload_crc(payload)
        out = json.dumps(payload, sort_keys=True,
                         separators=(",", ":"))
        path = os.path.join(self.spool_dir,
                            f"{job.id}.result.json")
        try:
            ensure_private_dir(self.spool_dir)
            write_durable_text(path, out)
        except OSError as e:
            if not self._spool_warned:
                self._spool_warned = True
                self._say(f"warning: cannot spool results "
                          f"({type(e).__name__}: {e}, first on "
                          f"{job.id}) — results stay in memory until "
                          "the spool dir is writable again; warning "
                          "once, counting every skip")
            self.obs.event("result_spool_error", job_id=job.id,
                           error=type(e).__name__)
            return
        self._spool_warned = False   # a successful spool re-arms the
        #                              warning: the NEXT outage logs
        job.spool = {"path": path, "bytes": len(out)}
        job.stats = None
        job.stderr_tail = ""
        job.flight = None     # the spool file holds it now — RAM
        #                       keeps only the index row
        self.ledger.add("spool", len(out))
        self.obs.event("result_spool", job_id=job.id,
                       bytes=len(out))

    def _load_spool(self, job: Job):
        """(payload, error) read back from the job's spool file,
        CRC-verified (the ckpt-v2 rule: a result that fails
        verification is reported unreadable, never served as if
        whole).  The payload dict carries stats, stderr_tail, and —
        since ISSUE 11 — the job's trace_id and flight record."""
        return load_spool_payload(job.spool["path"])

    def _unlink_spool(self, job: Job) -> None:
        if job.spool is None:
            return
        try:
            os.unlink(job.spool["path"])
        except OSError:
            pass
        self.ledger.sub("spool", job.spool.get("bytes", 0))
        job.spool = None

    def _evict_results(self) -> None:
        """Drop TERMINAL job results past ``--result-ttl-s`` and/or
        beyond ``--max-results`` (least-recently-accessed first).
        Running/queued jobs are never candidates; a client holding the
        Job object (blocked in ``result``) keeps its reference — only
        the id lookup goes away."""
        if self.result_ttl_s is None and self.max_results is None:
            return
        now = time.time()
        with self._lock:
            terminal = [j for j in self.jobs.values()
                        if j.state in TERMINAL_STATES
                        and j.done.is_set()]
            victims = []
            if self.result_ttl_s is not None:
                victims = [j for j in terminal
                           if now - (j.finished_s or j.submitted_s)
                           > self.result_ttl_s]
            if self.max_results is not None:
                keep = [j for j in terminal if j not in victims]
                excess = len(keep) - self.max_results
                if excess > 0:
                    keep.sort(key=lambda j: j.accessed_s)
                    victims += keep[:excess]
            for j in victims:
                self.jobs.pop(j.id, None)
        for j in victims:
            self._unlink_spool(j)      # eviction bounds DISK too: the
            #                            spool file goes with the entry
            self._journal_append(REC_EVICT, job_id=j.id)
            self.stats.jobs_evicted += 1
            self.svc_metrics["results_evicted"].inc()
            self.obs.event("job_evict", job_id=j.id, state=j.state,
                           trace_id=j.trace_id)

    def _drained(self) -> bool:
        with self._lock:
            return self._draining and not self._running \
                and self.queue.depth() == 0

    def _begin_drain(self, reason: str) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            running = list(self._running.values())
        self.leases.drain()    # wake lease-waiters empty-handed: their
        #                        jobs are preempted below by the worker
        waiting = self.queue.drain()
        # delta-HELD streams are queued-but-not-in-the-queue: the
        # drain must preempt them too or they hang forever
        with self._lock:
            held = [j for j in self.jobs.values()
                    if j.state == JOB_QUEUED and j.dstate is not None
                    and j.dstate.get("mode") == "holding"]
        for j in held:
            j.dstate["mode"] = "off"
        for job in waiting + held:
            self._retire_stream(job)
            job.state = JOB_PREEMPTED
            job.rc = EXIT_PREEMPTED
            job.detail = ("preempted before start (service drained); "
                          "resubmit to a live service — with --resume "
                          "if a previous attempt checkpointed")
            job.finished_s = time.time()
            self.stats.jobs_preempted += 1
            self.svc_metrics["jobs"].inc(outcome="preempted")
            self._journal_append(REC_FINISH, job_id=job.id,
                                 state=JOB_PREEMPTED,
                                 rc=EXIT_PREEMPTED,
                                 detail=job.detail)
            job.done.set()
        for job in running:
            if job.drain is not None:
                job.drain.request(reason)
        self.obs.event("service_drain", reason=reason,
                       running=len(running), preempted=len(waiting))
        self._say(f"draining ({reason}): {len(running)} in-flight "
                  f"job(s) finishing at their batch boundaries, "
                  f"{len(waiting)} queued job(s) preempted, new "
                  "submissions rejected")

    def _fence(self, reason: str) -> None:
        """Self-fence (ISSUE 16): the epoch lease is gone, so the
        router may ALREADY have re-admitted our jobs to siblings —
        from this instant every write we could make races the new
        owner.  Drain in-flight work to its durable checkpoints and
        preempt the queue, but — unlike a drain — do NOT latch
        admission or kill the workers: a fence is a pause (the next
        accepted lease lifts it), a drain is an exit."""
        if not self.epoch_lease.fence(reason):
            return                   # already fenced
        with self._lock:
            running = list(self._running.values())
        waiting = self.queue.preempt_all()
        for job in waiting:
            self._retire_stream(job)
            job.state = JOB_PREEMPTED
            job.rc = EXIT_PREEMPTED
            job.detail = ("preempted by fencing (member lost its "
                          "epoch lease); resubmit to the fleet — "
                          "with --resume if a previous attempt "
                          "checkpointed")
            job.finished_s = time.time()
            self.stats.jobs_preempted += 1
            self.svc_metrics["jobs"].inc(outcome="preempted")
            self._journal_append(REC_FINISH, job_id=job.id,
                                 state=JOB_PREEMPTED,
                                 rc=EXIT_PREEMPTED,
                                 detail=job.detail)
            job.done.set()
        for job in running:
            if job.drain is not None:
                job.drain.request(f"fenced: {reason}")
        self.svc_metrics["fences"].inc()
        self.obs.event("fenced", reason=reason,
                       epoch=self.epoch_lease.epoch,
                       running=len(running), preempted=len(waiting))
        self._say(f"FENCED ({reason}): {len(running)} in-flight "
                  f"job(s) draining to checkpoints, {len(waiting)} "
                  "queued job(s) preempted; refusing new work until "
                  "a fresh lease arrives")

    def _lease_grant(self, obj) -> tuple[bool, str]:
        """Apply one router lease heartbeat; returns (accepted,
        detail).  An accepted grant on a fenced member UN-fences it —
        the router has re-asserted ownership at a current epoch."""
        if not isinstance(obj, dict):
            return False, "lease must be an object {epoch, ttl_s}"
        was_fenced = self.epoch_lease.fenced
        ok, detail = self.epoch_lease.grant(obj.get("epoch"),
                                            obj.get("ttl_s"))
        if ok:
            self.svc_metrics["member_epoch"].set(
                self.epoch_lease.epoch)
            if was_fenced:
                self.obs.event("unfenced",
                               epoch=self.epoch_lease.epoch)
                self._say(f"lease re-granted at epoch "
                          f"{self.epoch_lease.epoch} — fence lifted, "
                          "accepting work again")
        return ok, detail

    # ---- workers -------------------------------------------------------
    def _worker(self) -> None:
        while not self._closing.is_set():
            job = self.queue.take(timeout=0.1)
            if job is None:
                if self._draining:
                    return
                continue
            # lease-aware admission (ISSUE 8): a dequeued job runs only
            # once it holds a device lane — with lanes < workers this
            # wait is real (and measured); a drain while waiting
            # preempts the job exactly like one still queued.  ONE
            # blocking acquire holds ONE FIFO ticket for the whole
            # wait (a short-timeout retry loop would re-enqueue at the
            # back each round, reordering two waiting jobs); drain
            # wakes the ticket empty-handed, and should_abort covers
            # the drain-less close path
            # flight accounting (ISSUE 11): queue wait ends at this
            # dequeue; the lease wait is its own phase — the two must
            # not overlap or the accounted sum overshoots the wall
            queue_wait = max(0.0,
                             time.monotonic() - job.submitted_mono)
            if job.flight is not None:
                job.flight.note("queue_wait", queue_wait)
            t_wait = time.monotonic()
            lease = self.leases.acquire(
                should_abort=self._closing.is_set,
                prefer_lane=job.prefer_lane)
            if lease is None:        # drained, or closing mid-wait
                self._preempt_leaseless(job)
                continue
            waited = time.monotonic() - t_wait
            self.svc_metrics["lease_wait_seconds"].observe(waited)
            if job.flight is not None:
                job.flight.note("lease_wait", waited,
                                lane=lease.lane)
            if self.obs.tracer is not None:
                # the daemon's trace timeline: queue + lease waits as
                # back-to-back complete spans (explicit end times —
                # the queue wait ends EXACTLY where the lease wait
                # starts, preserving the monotonic-nesting schema),
                # stamped with the job's trace_id so trace-merge can
                # follow one job across both processes
                now = self.obs.tracer.now()
                self.obs.tracer.complete(
                    "job_queue_wait", now - waited - queue_wait,
                    now - waited, job_id=job.id,
                    trace_id=job.trace_id)
                self.obs.tracer.complete(
                    "job_lease_wait", now - waited, now,
                    job_id=job.id, trace_id=job.trace_id,
                    lane=lease.lane)
            with self._lock:
                self._running[job.id] = job
            try:
                self._run_job(job, lease)
            finally:
                self.leases.release(lease)
                with self._lock:
                    self._running.pop(job.id, None)
                self._retire_stream(job)
                job.done.set()

    def _run_warmup(self) -> None:
        """``--warmup``: one tiny deterministic job through the normal
        supervised path (``cli.warmup_files`` corpus) on a free lane —
        the jax import, the backend probe and the smallest pow2-bucket
        program compiles are paid NOW, in the background, instead of
        under the first real job; with ``--compile-cache-dir`` the
        compiles also persist for the next restart.  Best-effort: a
        failed warmup costs a warning, never the daemon."""
        import io
        t0 = time.monotonic()
        lease = self.leases.acquire(
            should_abort=lambda: (self._closing.is_set()
                                  or self.drain.requested))
        if lease is None:
            return
        try:
            from pwasm_tpu.cli import warmup_files
            wdir = os.path.join(self._jobdir.name, "warmup")
            paf, fa = warmup_files(wdir)
            out = os.path.join(wdir, "warm.dfa")
            device = self.warmup if self.warmup in ("cpu", "tpu") \
                else "tpu"
            argv = [paf, "-r", fa, "-o", out, f"--device={device}",
                    "--batch=8"]
            drain = SignalDrain(stderr=self.stderr,
                                hard_exit=lambda code: None)
            warm = _JobWarm(self.warm, drain, lease,
                            expose_devices=self._expose_devices)
            self.obs.event("warmup_start", device=device,
                           lane=lease.lane)
            rc = self._runner(argv, stdout=io.StringIO(),
                              stderr=io.StringIO(), warm=warm)
            wall = round(time.monotonic() - t0, 3)
            self.obs.event("warmup_done", rc=rc, wall_s=wall,
                           lane=lease.lane)
            self._say(f"warmup ({device}) done in {wall}s (rc {rc})")
        except BaseException as e:   # never take the daemon down
            self._say(f"warning: warmup failed "
                      f"({type(e).__name__}: {e})")
        finally:
            self.leases.release(lease)

    def _retire_stream(self, job: Job) -> None:
        """A stream job leaving the live set: drop it from the quota
        book and latch its feed shut, so later ``stream-data`` frames
        answer an error instead of buffering records nobody will ever
        read."""
        if not job.stream:
            return
        self.streams.unregister(job.id)
        if job.feed is not None:
            job.feed.end()

    def _preempt_leaseless(self, job: Job) -> None:
        """A dequeued job the drain caught BEFORE it got a lease: same
        contract as one still queued — preempted, resumable, never
        started."""
        self._retire_stream(job)
        job.state = JOB_PREEMPTED
        job.rc = EXIT_PREEMPTED
        job.detail = ("preempted waiting for a device lease (service "
                      "drained); resubmit to a live service — with "
                      "--resume if a previous attempt checkpointed")
        job.finished_s = time.time()
        self.stats.jobs_preempted += 1
        self.svc_metrics["jobs"].inc(outcome="preempted")
        self._journal_append(REC_FINISH, job_id=job.id,
                             state=JOB_PREEMPTED, rc=EXIT_PREEMPTED,
                             detail=job.detail)
        self.obs.event("job_preempt_leaseless", job_id=job.id)
        job.done.set()

    def _deadline_remaining_s(self, job: Job) -> float | None:
        """The job's remaining end-to-end budget in seconds (ISSUE
        18): the admitted ``deadline_ms`` minus everything spent since
        admission — queue wait and lease wait included, measured on
        the monotonic clock.  None when the job carries no deadline."""
        if job.deadline_ms is None:
            return None
        return (job.deadline_ms / 1000.0
                - (time.monotonic() - job.submitted_mono))

    def _finish_deadline_spent(self, job: Job) -> None:
        """A job whose end-to-end budget ran out before exec (queue +
        lease wait ate it): land terminal WITHOUT running — rc 75,
        the same resumable contract a drain preemption gives, detail
        prefixed ``deadline_exceeded`` so clients and the router can
        tell a budget expiry from a drain.  Journaled truthfully (a
        finish with no start record — the job never ran)."""
        job.state = JOB_PREEMPTED
        job.rc = EXIT_PREEMPTED
        job.detail = ("deadline_exceeded: the end-to-end budget "
                      f"({job.deadline_ms} ms at admission) was spent "
                      "in queue + lease wait before exec; resubmit "
                      "with --resume and a fresh --deadline-s")
        job.finished_s = time.time()
        self.stats.jobs_preempted += 1
        self.stats.jobs_deadline_exceeded += 1
        self.svc_metrics["jobs"].inc(outcome="deadline_exceeded")
        self._journal_append(REC_FINISH, job_id=job.id,
                             state=JOB_PREEMPTED, rc=EXIT_PREEMPTED,
                             detail=job.detail)
        self.obs.event("job_deadline_exceeded", job_id=job.id,
                       trace_id=job.trace_id, ran=False)

    def _run_job(self, job: Job, lease) -> None:
        # end-to-end deadline (ISSUE 18): subtract the queue + lease
        # wait from the admitted budget HERE, at the exec boundary —
        # a spent budget lands terminal without burning a device
        # second; a live one rides into the run as --deadline-s, where
        # the cli's drain timer enforces it at batch boundaries
        remaining_s = self._deadline_remaining_s(job)
        if remaining_s is not None and remaining_s <= 0:
            self._finish_deadline_spent(job)
            return
        job.state = JOB_RUNNING
        job.started_s = time.time()
        if job.stream:
            # lane affinity for the client's NEXT stream (and, via the
            # journal's start record, for a crash-reopened one)
            with self._lock:
                self._client_lanes[job.client] = lease.lane
        # journal the start BEFORE the run: a kill -9 from here on
        # makes the job a --resume continuation at the next start
        self._journal_append(REC_START, job_id=job.id,
                             lane=lease.lane)
        self.obs.event("job_start", job_id=job.id, lane=lease.lane,
                       trace_id=job.trace_id,
                       queue_wait_s=round(job.started_s
                                          - job.submitted_s, 6))
        # a drain latched between this job's dequeue and here must
        # still reach its flag (the _begin_drain snapshot may have
        # missed it)
        if self.drain.requested and job.drain is not None \
                and not job.drain.requested:
            job.drain.request(self.drain.reason or "service draining")
        warm = _JobWarm(self.warm, job.drain, lease,
                        expose_devices=self._expose_devices,
                        trace_id=job.trace_id, flight=job.flight)
        rc: int | None = None
        kw = {"input_stream": job.feed} if job.stream else {}
        exec_argv = job.argv
        if remaining_s is not None:
            # pass the REMAINING budget down, not the original: the
            # run's own --deadline-s timer then enforces exactly what
            # is left after this daemon's queue + lease wait
            exec_argv = list(job.argv) \
                + [f"--deadline-s={max(remaining_s, 0.001):.3f}"]
        try:
            with self.obs.span("job_exec", job_id=job.id,
                               trace_id=job.trace_id,
                               lane=lease.lane):
                rc = self._runner(exec_argv, stdout=job.outbuf,
                                  stderr=job.errbuf, warm=warm, **kw)
        except BaseException as e:   # InjectedKill, stray PwasmError —
            # a dying job must never take the daemon down with it
            job.detail = f"job raised {type(e).__name__}: {e}"
        job.rc = rc
        job.finished_s = time.time()
        if job.flight is not None:
            job.flight.note("exec", max(
                0.0, job.finished_s - job.started_s),
                lane=lease.lane, rc=rc)
        self._job_walls.append(job.finished_s - job.started_s)
        job.stderr_tail = job.errbuf.getvalue()[-4000:]
        # a resident daemon must not retain every finished job's full
        # output buffers for its whole life: keep only the served tail
        # and drop the StringIOs (re-pointing the job's drain at the
        # daemon stderr first — a late message must not hit a dropped
        # buffer)
        if job.drain is not None:
            job.drain.stderr = self.stderr
        job.errbuf = job.outbuf = None
        job.stats = self._read_job_stats(job)
        if isinstance(job.stats, dict) \
                and isinstance(job.stats.get("m2m"), dict):
            # fold a finished surveillance session's flow into the
            # cumulative svc-stats "m2m" block (ISSUE 20)
            m = job.stats["m2m"]
            with self._lock:
                self._m2m_done["sessions"] += 1
                for k in self._m2m_done:
                    if k != "sessions":
                        try:
                            self._m2m_done[k] += int(m.get(k, 0) or 0)
                        except (TypeError, ValueError):
                            pass
            self.m2m_metrics["sessions"].inc()
            for k, fam in (("targets_in", "targets_in"),
                           ("targets_scored", "targets_scored"),
                           ("targets_reused", "targets_reused"),
                           ("pairs_dispatched", "pairs_dispatched"),
                           ("pairs_reused", "pairs_reused"),
                           ("batches", "batches"),
                           ("sections_emitted", "sections")):
                try:
                    v = int(m.get(k, 0) or 0)
                except (TypeError, ValueError):
                    continue
                if v > 0:
                    self.m2m_metrics[fam].inc(v)
        if rc == 0 and job.delta is not None and job.stream \
                and job.feed is not None:
            # a held stream's promote fixed its served count when only
            # part of the input had arrived: the truthful TOTAL is the
            # whole stream, known at finish
            job.delta = (job.delta[0],
                         max(job.delta[1], job.feed.records_in))
        if rc == 0 and job.delta is not None:
            # the fractional hit lands at FINISH, not admission — a
            # failed tail run must not count as served traffic
            if self.cache is not None:
                self.cache.note_delta(*job.delta)
            if isinstance(job.stats, dict):
                job.stats["cache_delta"] = True
                job.stats["cache_records_served"] = job.delta[0]
                job.stats["cache_records_total"] = job.delta[1]
                if job.stats_path is not None \
                        and not job.stats_injected:
                    # the client's own --stats artifact must tell the
                    # same truth the result frame does: the tail run
                    # didn't know it was a delta, so stamp it here
                    try:
                        import json as _json
                        with open(job.stats_path, "w") as f:
                            _json.dump(job.stats, f, indent=1)
                            f.write("\n")
                    except OSError:
                        pass
        if rc == 0:
            job.state = JOB_DONE
            self.stats.jobs_completed += 1
        elif rc == EXIT_PREEMPTED and job.cancel_requested:
            job.state = JOB_CANCELLED
            job.detail = ("cancelled at a batch boundary; the partial "
                          "report is checkpointed (resumable)")
            self.stats.jobs_cancelled += 1
        elif rc == EXIT_PREEMPTED and job.drain is not None \
                and str(job.drain.reason
                        or "").startswith("deadline_exceeded"):
            # the run's own --deadline-s timer pulled the drain flag:
            # same resumable shape as a drain preemption, but the
            # verdict must say WHY — the client decides whether a
            # resume deserves a fresh budget
            job.state = JOB_PREEMPTED
            job.detail = ("deadline_exceeded: stopped at a batch "
                          "boundary with a valid resumable "
                          "checkpoint; --resume with a fresh "
                          "--deadline-s completes it")
            self.stats.jobs_preempted += 1
            self.stats.jobs_deadline_exceeded += 1
            self.obs.event("job_deadline_exceeded", job_id=job.id,
                           trace_id=job.trace_id, ran=True)
        elif rc == EXIT_PREEMPTED:
            job.state = JOB_PREEMPTED
            job.detail = ("preempted by service drain; --resume "
                          "completes it")
            self.stats.jobs_preempted += 1
        else:
            job.state = JOB_FAILED
            if not job.detail:
                job.detail = f"exit {rc}"
            self.stats.jobs_failed += 1
        self.stats.rollup_job(job.stats)
        # fold the finished job into the Prometheus surface: outcome
        # counter, wall + queue-wait histograms, and the job's --stats
        # JSON into the cumulative run-level families (the same fold
        # the one-shot CLI applies to itself — obs/catalog.py)
        from pwasm_tpu.obs.catalog import fold_run_stats
        self.svc_metrics["jobs"].inc(outcome=job.state)
        self.svc_metrics["lane_jobs"].inc(lane=str(lease.lane))
        # exemplar-linked (ISSUE 14 satellite): the bucket this job
        # landed in carries its trace_id, so a p99 bucket in the
        # exposition links straight to `pwasm-tpu inspect <job>`
        self.svc_metrics["job_wall_seconds"].observe(
            job.finished_s - job.started_s, trace_id=job.trace_id)
        self.svc_metrics["queue_wait_seconds"].observe(
            max(0.0, job.started_s - job.submitted_s),
            trace_id=job.trace_id)
        fold_run_stats(self.run_metrics, job.stats)
        if job.state == JOB_DONE and job.cache is not None \
                and self.cache is not None:
            # insert at job finish (ISSUE 15): the outputs this run
            # just wrote become the entry an identical later submit
            # is answered from at admission
            self._cache_insert(job)
        elif job.state == JOB_DONE and job.dstate is not None \
                and self.cache is not None:
            # a delta-mirrored stream inserts too (ROADMAP 4c): its
            # digest column is the delta index a later stream or file
            # job in the same family extends
            self._stream_cache_insert(job)
        # past every RAM consumer of job.stats: big results move to
        # the spool (index-only in RAM), then the terminal verdict —
        # with its spool pointer — lands durably in the journal
        self._spool_result(job)
        self._journal_append(REC_FINISH, job_id=job.id,
                             state=job.state, rc=rc,
                             detail=job.detail or None,
                             spool=job.spool)
        self.obs.event(
            "job_finish", job_id=job.id, state=job.state, rc=rc,
            lane=lease.lane, trace_id=job.trace_id,
            wall_s=round(job.finished_s - job.started_s, 6),
            detail=job.detail or None)
        self._write_textfile()

    def _read_job_stats(self, job: Job) -> dict | None:
        if job.stats_path is None:
            return None
        try:
            import json
            with open(job.stats_path) as f:
                st = json.load(f)
        except (OSError, ValueError):
            return None
        if job.stats_injected:
            try:
                os.unlink(job.stats_path)
            except OSError:
                pass
        return st if isinstance(st, dict) else None

    # ---- admission -----------------------------------------------------
    def _arm_job(self, job: Job) -> None:
        """Per-job drain flag + RunStats sink (a daemon-owned stats
        tmp is injected when the client didn't pass ``--stats`` — the
        daemon needs every job's RunStats for the roll-up and warm-hit
        gates) + the flight recorder (ISSUE 11).  Shared by fresh
        admissions and journal recovery."""
        from pwasm_tpu.obs.flight import FlightRecorder
        job.flight = FlightRecorder(trace_id=job.trace_id or None)
        if job.recovered:
            job.flight.mark("journal_recovered")
        job.drain = SignalDrain(stderr=job.errbuf,
                                hard_exit=lambda code: None)
        stats_path = next(
            (a.split("=", 1)[1] for a in job.argv
             if a.startswith("--stats=")), None)
        if stats_path is None:
            stats_path = os.path.join(self._jobdir.name,
                                      f"{job.id}.stats.json")
            job.argv = job.argv + [f"--stats={stats_path}"]
            job.stats_injected = True
        job.stats_path = stats_path

    def submit(self, argv: list, cwd: str | None = None,
               client: str | None = None,
               priority: str | None = None,
               stream: bool = False,
               trace_id: str | None = None,
               deadline_ms: int | None = None,
               delta: bool = False) -> Job:
        """Validate + admit one job (raises Draining/QueueFull/
        ValueError).  Also the in-process API the tests drive.
        ``cwd`` is the CLIENT's working directory: relative paths in
        the job argv are resolved against it, not the daemon's cwd —
        the cold-to-warm drop-in contract (the client sends it
        automatically).  ``client`` is the fair-share identity (the
        protocol layer defaults it to the socket-peer uid);
        ``priority`` must name a ``--priority-lanes`` tier when
        given.  ``stream=True`` admits a SOCKET-STREAM job (the
        ``stream`` protocol verb): its PAF records arrive later as
        ``stream-data`` frames, so the argv must carry no positional
        input, and the job gets a quota-gated StreamFeed plus lane
        affinity to the client's previous stream."""
        if not isinstance(argv, list) \
                or not all(isinstance(a, str) for a in argv) \
                or not argv:
            raise ValueError("args must be a non-empty list of strings")
        if client is None:
            client = ""
        if not isinstance(client, str) or len(client) > 64 \
                or not _CLIENT_RE.match(client or "x"):
            raise ValueError(
                "client must be a short identifier "
                "([A-Za-z0-9_.:@/-]{1,64})")
        if priority is None:
            priority = ""
        if not isinstance(priority, str):
            raise ValueError("priority must be a string")
        # cross-process trace identity (ISSUE 11): ServiceClient mints
        # one and sends it on every frame; a frame without one (an
        # older client, a hand-rolled nc pipe) gets a daemon-minted id
        # so EVERY job is trace-correlatable
        if trace_id is None or trace_id == "":
            from pwasm_tpu.obs.events import new_run_id
            trace_id = new_run_id()
        if not isinstance(trace_id, str) or len(trace_id) > 64 \
                or not _CLIENT_RE.match(trace_id):
            raise ValueError(
                "trace_id must be a short identifier "
                "([A-Za-z0-9_.:@/-]{1,64})")
        if deadline_ms is not None:
            # the REMAINING end-to-end budget as of this hop (ISSUE
            # 18); 0/negative is valid on the wire — the DISPATCH
            # layer answers it deadline_exceeded before calling here
            if isinstance(deadline_ms, bool) \
                    or not isinstance(deadline_ms, int) \
                    or deadline_ms <= 0:
                raise ValueError(
                    "deadline_ms must be a positive integer "
                    "millisecond budget")
        if priority:
            lanes = [l for l in self.queue.priority_lanes if l]
            if not lanes:
                raise ValueError(
                    "this daemon has no --priority-lanes configured")
            if priority not in lanes:
                raise ValueError(
                    f"unknown priority lane {priority!r} "
                    f"(configured: {','.join(lanes)})")
        from pwasm_tpu.cli import _SERVICE_CMDS, _parse_args, CliError
        if argv[0] in _SERVICE_CMDS:
            raise ValueError(
                f"nested service command {argv[0]!r} not allowed")
        if cwd is not None:
            if not isinstance(cwd, str) or not os.path.isabs(cwd):
                raise ValueError("cwd must be an absolute path")
            argv = _absolutize_argv(argv, cwd)
        # parse with the REAL CLI grammar (clustered short flags like
        # `-Do out` included) so the cold-to-warm drop-in contract
        # cannot drift from what cli.run would accept
        try:
            job_opts, _pos = _parse_args(list(argv))
        except CliError as e:
            raise ValueError(f"unparseable job argv: "
                             f"{str(e).splitlines()[-1]}")
        if "o" not in job_opts:
            raise ValueError(
                "service jobs must write their report to a file "
                "(-o <report>): the socket carries control frames, "
                "not report bytes")
        if stream:
            if _pos:
                raise ValueError(
                    "stream jobs read records from stream-data "
                    "frames: drop the positional PAF path "
                    f"({_pos[0]!r})")
            for bad in ("follow", "many2many"):
                if bad in job_opts:
                    raise ValueError(
                        f"--{bad} does not apply to a socket stream")
        if self.drain.requested:
            raise Draining("service is draining")
        # ---- content-addressed result cache (ISSUE 15): the lookup
        # happens HERE, at admission, before queue.submit — a hit
        # never touches the queue, a lease, or a device (the ≥100x
        # path).  Streams bypass (their input is not a file); a miss
        # remembers the key so the finished job inserts its outputs.
        cache_row = None
        delta_served = None
        if self.cache is not None and not stream:
            from pwasm_tpu.service.cache import classify_argv, \
                derive_key
            cls = classify_argv(argv)
            key = derive_key(cls) if cls is not None else None
            if key is not None:
                got = self.cache.get(key)
                if got is not None:
                    from pwasm_tpu.service.cache import serve_outputs
                    manifest, blobs = got
                    served = False
                    try:
                        served = serve_outputs(blobs,
                                               cls.output_paths)
                    except OSError:
                        served = False   # unwritable output: the real
                        #   run below reports the real diagnostic
                    if served:
                        return self._admit_cache_hit(
                            argv, client, priority, trace_id,
                            manifest)
                cache_row = (key, cls)
                # exact miss (ISSUE 17a): a same-family entry whose
                # input is a per-line PREFIX of ours serves its cached
                # report bytes NOW and re-arms the job as a --resume
                # over them — the worker recomputes only the last
                # cached record and the appended tail.  The journal
                # admit keeps the ORIGINAL argv: a crash-replay
                # re-runs the job cold, which is always correct.
                delta_served = self._admit_cache_delta(cls)
        base_argv = list(argv)     # what the journal records: the
        #   pre-injection argv (the injected stats tmp lives in a
        #   directory that dies with this process)
        exec_argv = list(argv)
        if delta_served is not None:
            exec_argv.append("--resume")
        with self._lock:
            self._next_id += 1
            job = Job(id=f"job-{self._next_id:04d}", argv=exec_argv,
                      client=client, priority=priority,
                      trace_id=trace_id)
        job.deadline_ms = deadline_ms   # the monotonic anchor is
        #   Job.submitted_mono (defaulted at construction, just now)
        job.cache = cache_row      # (key, classified) on a cacheable
        #   miss: _run_job inserts the finished outputs under it
        job.delta = delta_served
        self._arm_job(job)
        if stream:
            from pwasm_tpu.stream.pafstream import StreamFeed
            job.stream = True
            job.feed = StreamFeed(idle_timeout_s=self.stream_idle_s)
            # the drain flag wakes a feed-blocked job; the batch hook
            # feeds the per-client arrival-batch counter
            job.feed.bind_drain(job.drain)
            job.feed.on_batch = \
                lambda n, c=(client or "default"): \
                self.stream_metrics["batches"].inc(1, client=c)
            # lane affinity: a client's consecutive (or crash-reopened)
            # streams land on the lane whose warm state they built
            with self._lock:
                job.prefer_lane = self._client_lanes.get(client)
            self.streams.register(job.id, client, job.feed)
            if delta and self.cache is not None:
                # delta over the SOCKET (ROADMAP 4c): the client
                # volunteered per-line digests, so this stream can be
                # classified against the cache's digest columns like a
                # file input.  While same-family candidates exist the
                # job is HELD out of the queue and its frames parked;
                # a strict-prefix match serves the cached report and
                # re-arms the job as a --resume over it, exactly the
                # file-side _admit_cache_delta shape.
                job.dstate = self._delta_stream_open(job_opts)
        # write-ahead order: the admit record lands BEFORE the queue
        # can hand the job to a worker — a worker only journals start
        # after a successful dequeue, so the file order admit < start
        # that replay's fold depends on cannot invert.  (It also lands
        # before the ok frame, so every ACKED admission is durable; a
        # crash in the gap between append and ack at worst re-runs a
        # job nobody was promised — the benign direction.)
        self._journal_append(REC_ADMIT, job_id=job.id,
                             argv=base_argv, client=client,
                             priority=priority, trace_id=trace_id,
                             **({"stream": True} if stream else {}),
                             **({"deadline_ms": deadline_ms}
                                if deadline_ms else {}))
        if delta_served is not None:
            # truthful journal shape: a delta job is NOT a pure hit —
            # the cache_hit record carries the computed-vs-served
            # split, and the start/finish records that follow show the
            # real (tail-only) run
            self._journal_append(REC_CACHE_HIT, job_id=job.id,
                                 delta=True, served=delta_served[0],
                                 total=delta_served[1])
            self.obs.event("cache_delta", job_id=job.id,
                           trace_id=job.trace_id,
                           served=delta_served[0],
                           total=delta_served[1])
        try:
            # a delta-HELD stream defers its queue entry: it either
            # promotes to a --resume (frames decide) or goes cold at
            # the viability/cap/end boundary — _delta_stream_queue
            if not (job.dstate is not None
                    and job.dstate.get("mode") == "holding"):
                self.queue.submit(job)
        except (Draining, QueueFull):
            # the admission never happened: retract the id so replay
            # cannot resurrect a job the client was told was rejected
            self._journal_append(REC_EVICT, job_id=job.id)
            if stream:
                self.streams.unregister(job.id)
            raise
        with self._lock:
            self.jobs[job.id] = job
            self._clients_seen.add(client)
        self.stats.jobs_accepted += 1
        self.svc_metrics["jobs"].inc(outcome="accepted")
        self.obs.event("job_admit", job_id=job.id, client=client,
                       trace_id=job.trace_id, stream=stream,
                       queue_depth=self.queue.depth())
        return job

    def _admit_cache_hit(self, argv: list, client: str, priority: str,
                         trace_id: str, manifest: dict) -> Job:
        """Admit-and-finish a job answered from the result cache: the
        output files are already written from the CRC-verified blobs,
        so the job lands terminal DONE without ever entering the
        queue.  Journaled as admit + cache_hit + finish, so a replay
        (or a failover router reading this journal) restores a
        truthful terminal verdict — a finish with no start record,
        explained by the cache_hit line."""
        from pwasm_tpu.service.cache import (argv_stats_path,
                                             write_hit_stats)
        with self._lock:
            self._next_id += 1
            job = Job(id=f"job-{self._next_id:04d}", argv=list(argv),
                      client=client, priority=priority,
                      trace_id=trace_id)
        job.state = JOB_DONE
        job.rc = 0
        job.detail = ("served from the result cache "
                      "(byte-identical to a full run)")
        # a --stats-asking client gets the same file artifact a
        # cold-run hit writes (one shared implementation across tiers)
        job.stats = write_hit_stats(manifest, argv_stats_path(argv))
        job.started_s = job.submitted_s
        job.finished_s = time.time()
        job.errbuf = job.outbuf = None
        # one durable append (one fsync) for the whole triple: a hit
        # pays one disk barrier, and the torn-tail rule still holds —
        # a crash mid-append drops a whole suffix, never a half-line
        if self.journal is not None:
            t = round(time.time(), 3)
            if self.journal.append_many([
                    (REC_ADMIT, {"job_id": job.id, "t": t,
                                 "argv": list(argv),
                                 "client": client,
                                 "priority": priority,
                                 "trace_id": trace_id}),
                    (REC_CACHE_HIT, {"job_id": job.id, "t": t}),
                    (REC_FINISH, {"job_id": job.id, "t": t,
                                  "state": JOB_DONE, "rc": 0,
                                  "detail": job.detail})]):
                for rec in (REC_ADMIT, REC_CACHE_HIT, REC_FINISH):
                    self.svc_metrics["journal_records"].inc(rec=rec)
            elif not self._journal_warned:
                self._journal_warned = True
                self._say(f"warning: job-journal append failed "
                          f"({self.journal.broken}); continuing "
                          "WITHOUT crash recovery")
                self.obs.event("journal_broken",
                               detail=self.journal.broken)
        job.done.set()
        with self._lock:
            self.jobs[job.id] = job
            self._clients_seen.add(client)
        self.stats.jobs_accepted += 1
        self.stats.jobs_completed += 1
        self.svc_metrics["jobs"].inc(outcome="accepted")
        self.svc_metrics["jobs"].inc(outcome=JOB_DONE)
        wall = max(0.0, job.finished_s - job.submitted_s)
        # the wall/wait histograms see the SERVED latency — the whole
        # point of the cache is that these observations collapse
        self.svc_metrics["job_wall_seconds"].observe(
            wall, trace_id=job.trace_id)
        self.svc_metrics["queue_wait_seconds"].observe(
            0.0, trace_id=job.trace_id)
        self.obs.event("job_admit", job_id=job.id, client=client,
                       trace_id=job.trace_id, stream=False,
                       queue_depth=self.queue.depth())
        self.obs.event("cache_hit", job_id=job.id,
                       trace_id=job.trace_id)
        self.obs.event("job_finish", job_id=job.id, state=JOB_DONE,
                       rc=0, trace_id=job.trace_id,
                       wall_s=round(wall, 6), detail=job.detail)
        self._write_textfile()   # a hit is a finished job too: the
        #                          scraper's view must not go stale on
        #                          a daemon serving pure repeat traffic
        return job

    def _admit_cache_delta(self, cls) -> tuple | None:
        """Exact-miss admission (ISSUE 17a): find a cached same-family
        entry whose input records are a strict per-line prefix of this
        job's, write its CRC-verified report bytes to the job's output
        path, and return ``(records served, records total)`` so the
        caller re-arms the job with ``--resume`` — the worker's
        header-scan resume then recomputes only the last cached record
        plus the appended tail.  ``None`` = run cold (any rot,
        unwritable output, or ineligible shape falls back silently:
        delta is an optimization, never a correctness gate)."""
        from pwasm_tpu.service.cache import (delta_eligible,
                                             derive_keys,
                                             paf_line_digests)
        if cls is None or not delta_eligible(cls):
            return None
        digests, _fdig = paf_line_digests(cls.input_path)
        if digests is None or len(digests) < 2:
            return None
        derived = derive_keys(cls)
        if derived is None:
            return None
        hit = self.cache.delta_lookup(derived[1], digests)
        if hit is None:
            return None
        _key, _manifest, blobs, nl = hit
        report = cls.output_paths.get("o")
        if report is None or "o" not in blobs:
            return None
        try:
            with open(report, "wb") as f:
                f.write(blobs["o"])
        except OSError:
            return None   # unwritable output: the real run below
            #   reports the canonical diagnostic
        from pwasm_tpu.cli import _unlink_checkpoint
        _unlink_checkpoint(report)   # the served bytes ARE the resume
        #   state — a stale ckpt must not hijack the header scan
        return (max(0, nl - 1), len(digests))

    # ---- delta over socket streams (ROADMAP 4c) ------------------------
    #
    # A file job's delta admission has the whole input in hand; a
    # stream's input arrives one frame at a time.  So the stream-delta
    # admission is a small state machine on Job.dstate:
    #
    #   holding  — frames are digested and PARKED (not fed, not
    #              queued) while any same-family cache entry could
    #              still prefix-match the growing digest column;
    #   resolved — the job is queued (as a --resume over served
    #              cached bytes, or cold); parked frames were
    #              replayed into the feed, and the daemon keeps
    #              mirroring the digest column so a clean finish
    #              inserts a delta-indexed entry of its own;
    #   off      — bookkeeping abandoned (cancel/drain while held).
    #
    # Digests are SERVER-authoritative: the client's advisory column
    # (stream-data "digests") is cross-checked, never trusted — a
    # disagreement is a loud bad_request, not a wrong serve.

    def _delta_stream_open(self, job_opts: dict) -> dict | None:
        """Classify a delta-opted stream against the cache; ``None``
        when the shape can never delta-match (bypass flag, non-report
        output, unreadable ref) — the stream then runs exactly as a
        non-delta stream."""
        from pwasm_tpu.service.cache import (DELTA_MAX_LINES,
                                             classify_stream,
                                             delta_eligible,
                                             stream_keys)
        from pwasm_tpu.stream.pafstream import LineAssembler
        cls = classify_stream(job_opts)
        if cls is None or not delta_eligible(cls):
            return None
        keys = stream_keys(cls, [])
        if keys is None:
            return None
        cands = self.cache.delta_index(keys[1])
        return {
            # no candidates = nothing to wait for: queue now, mirror
            # only (this stream still INSERTS a delta entry at finish)
            "mode": "holding" if cands else "resolved",
            "cls": cls, "family": keys[1],
            "digests": [], "held": [],
            "asm": LineAssembler(),
            "cands": cands,
            # parked lines stay under the per-stream buffer quota the
            # feed itself would have enforced
            "cap": min(self.streams.max_buffer, DELTA_MAX_LINES),
        }

    def _delta_stream_queue(self, job: Job) -> dict | None:
        """Late queue entry for a held stream; an error response means
        the hold state is UNCHANGED and the triggering frame (or
        stream-end) resends after backoff — the same all-or-nothing
        contract every stream frame already has."""
        try:
            self.queue.submit(job)
        except Draining as e:
            return protocol.err(protocol.ERR_DRAINING, str(e))
        except QueueFull as e:
            return protocol.err(
                protocol.ERR_QUEUE_FULL, str(e),
                queue_depth=self.queue.depth(),
                max_queue=self.queue.max_queue,
                retry_after_s=self._retry_after_s())
        return None

    def _delta_stream_replay(self, job: Job, extra: list,
                             end: bool = False) -> None:
        """Feed the parked frames (plus the triggering frame) into the
        now-queued job's StreamFeed, committing the digest mirror for
        the triggering frame as the feed commits its lines."""
        from pwasm_tpu.service.cache import line_digest
        ds = job.dstate
        feed = job.feed
        for fr in ds["held"] + list(extra):
            n = feed.completed(fr)
            if n:
                try:
                    self.streams.admit(job.id, n)
                except QueueFull:
                    # the hold cap bounded parked lines under the
                    # per-stream quota; a shared-total squeeze here is
                    # transient — backpressure resumes on the next
                    # LIVE frame, and dropping parked frames is not an
                    # option (they were acked)
                    pass
            fed = feed.feed(fr)
            if fed:
                self.stream_metrics["records"].inc(
                    fed, client=job.client or "default")
        for fr in extra:
            for ln in ds["asm"].push(fr):
                ds["digests"].append(line_digest(ln))
        ds["held"] = []
        if end:
            for tail in ds["asm"].flush():
                ds["digests"].append(line_digest(tail))
            feed.end()

    def _delta_stream_go_cold(self, job: Job, extra: list,
                              end: bool = False) -> dict | None:
        ds = job.dstate
        err = self._delta_stream_queue(job)
        if err is not None:
            return err
        self._delta_stream_replay(job, extra, end=end)
        ds["mode"] = "resolved"
        return None

    def _delta_stream_promote(self, job: Job, hit: tuple,
                              digests: list, extra: list,
                              end: bool = False) -> dict | None:
        """Serve a delta hit to a held stream: cached report bytes out,
        job re-armed as a --resume, queued, parked frames replayed.
        Falls back to a cold run on any write failure — delta is an
        optimization, never a correctness gate."""
        ds = job.dstate
        _key, _manifest, blobs, nl = hit
        report = ds["cls"].output_paths.get("o")
        served = None
        if report is not None and "o" in blobs:
            try:
                with open(report, "wb") as f:
                    f.write(blobs["o"])
                from pwasm_tpu.cli import _unlink_checkpoint
                _unlink_checkpoint(report)
                served = (max(0, nl - 1), len(digests))
            except OSError:
                served = None
        if served is None:
            return self._delta_stream_go_cold(job, extra, end=end)
        # arm BEFORE queueing — a worker may dequeue instantly, and it
        # must see the --resume and the served report
        job.argv.append("--resume")
        job.delta = served
        err = self._delta_stream_queue(job)
        if err is not None:
            # unwind so the client's verbatim resend re-resolves
            # cleanly (the rewritten report file is re-written then)
            job.argv.pop()
            job.delta = None
            return err
        self._journal_append(REC_CACHE_HIT, job_id=job.id,
                             delta=True, served=served[0],
                             total=served[1])
        self.obs.event("cache_delta", job_id=job.id,
                       trace_id=job.trace_id,
                       served=served[0], total=served[1])
        self._delta_stream_replay(job, extra, end=end)
        ds["digests"] = list(digests)
        ds["mode"] = "resolved"
        return None

    def _delta_stream_data(self, job: Job, req: dict,
                           data: str) -> dict:
        """One stream-data frame while HELD: digest its lines, decide
        hit / keep-holding / go-cold, answer the client."""
        from pwasm_tpu.service.cache import line_digest
        ds = job.dstate
        feed = job.feed
        asm = ds["asm"]
        lines = asm.preview(data)
        if not lines and data:
            from pwasm_tpu.stream.pafstream import MAX_RECORD_BYTES
            if len(asm.pending) + len(data) > MAX_RECORD_BYTES:
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    f"unterminated PAF record exceeds "
                    f"{MAX_RECORD_BYTES} bytes — stream-data frames "
                    "must eventually carry a newline")
        digs = [line_digest(ln) for ln in lines]
        cdigs = req.get("digests")
        if cdigs is not None and list(cdigs) != digs:
            return protocol.err(
                protocol.ERR_BAD_REQUEST,
                "stream-data digests disagree with the server's own "
                "line digests — refusing to classify this stream "
                "against the cache (client-side assembler bug?)")
        new = ds["digests"] + digs
        joined = "".join(new)
        # a candidate fully inside our column decides NOW
        if any(nl < len(new) and dx == joined[:len(dx)]
               for nl, dx in ds["cands"]):
            hit = self.cache.delta_lookup(ds["family"], new)
            if hit is not None:
                err = self._delta_stream_promote(job, hit, new,
                                                 extra=[data])
                if err is not None:
                    return err
                return protocol.ok(buffered=feed.buffered,
                                   records=feed.records_in)
            # snapshot rotted under us: refresh and fall through
            ds["cands"] = self.cache.delta_index(ds["family"])
        # still worth holding?  some candidate our column prefixes
        # (longer = future strict hit; equal = stream-end exact-length
        # hit) and the parked lines stay under the buffer quota
        viable = any(nl >= len(new) and dx[:len(joined)] == joined
                     for nl, dx in ds["cands"])
        if viable and len(new) <= ds["cap"]:
            ds["held"].append(data)
            ds["digests"] = new
            asm.push(data)
            return protocol.ok(buffered=len(new), records=len(new))
        err = self._delta_stream_go_cold(job, extra=[data])
        if err is not None:
            return err
        return protocol.ok(buffered=feed.buffered,
                           records=feed.records_in)

    def _delta_stream_finish(self, job: Job) -> dict:
        """stream-end while HELD: the column is final — one last
        lookup with exact-length matches allowed, then promote or run
        cold over the replayed frames."""
        from pwasm_tpu.service.cache import line_digest
        ds = job.dstate
        feed = job.feed
        tail = ds["asm"].pending
        final = ds["digests"] + ([line_digest(tail)] if tail else [])
        hit = self.cache.delta_lookup(ds["family"], final,
                                      allow_equal=True) \
            if len(final) >= 2 else None
        if hit is not None:
            err = self._delta_stream_promote(job, hit, final,
                                             extra=[], end=True)
        else:
            err = self._delta_stream_go_cold(job, extra=[], end=True)
        if err is not None:
            return err
        return protocol.ok(records=feed.records_in,
                           buffered=feed.buffered)

    def _stream_cache_insert(self, job: Job) -> None:
        """A cleanly finished delta-mirrored stream becomes a cache
        entry with a per-line delta index — the next stream (or FILE
        job: the family namespace is shared) that extends this one is
        served as a delta.  Every guard degrades to 'no insert'."""
        from pwasm_tpu.service.cache import (DELTA_MAX_LINES,
                                             stream_keys)
        ds = job.dstate
        feed = job.feed
        digests = ds.get("digests") or []
        if ds.get("mode") != "resolved" or feed is None \
                or not feed.ended \
                or feed.records_in != len(digests) \
                or len(digests) < 2 or len(digests) > DELTA_MAX_LINES:
            return
        keys = stream_keys(ds["cls"], digests)
        if keys is None:
            return
        report = ds["cls"].output_paths.get("o")
        if report is None:
            return
        try:
            with open(report, "rb") as f:
                blob = f.read()
        except OSError:
            return
        if self.cache.insert(
                keys[0], {"o": blob}, stats=job.stats,
                delta={"family": keys[1], "lines": len(digests),
                       "dx": "".join(digests).encode("ascii")}):
            self.obs.event("cache_insert", job_id=job.id,
                           trace_id=job.trace_id)

    def _cache_insert(self, job: Job) -> None:
        """Store a cleanly finished job's output files under its
        admission-time key via the shared ``insert_from_paths`` (one
        populate implementation with the cold CLI): the key re-derive
        inside it skips the insert when the input was rewritten
        between admission and finish — a drifted key must never be
        poisoned."""
        key, cls = job.cache
        from pwasm_tpu.service.cache import insert_from_paths
        if insert_from_paths(self.cache, key, cls, stats=job.stats):
            self.obs.event("cache_insert", job_id=job.id,
                           trace_id=job.trace_id)
            self._cache_insert_warned = False   # writable again: the
            #                                     next outage warns
        elif not self._cache_insert_warned:
            # pass-through degradation (ISSUE 18 satellite): the job
            # was served from its real run — only the cache write was
            # skipped (full disk, drifted key, unreadable output).
            # One warning per outage; insert_errors counts each skip.
            self._cache_insert_warned = True
            self._say(f"warning: result-cache insert skipped (first "
                      f"on {job.id}) — serving continues without "
                      "caching; see cache.insert_errors / "
                      "pwasm_cache_insert_errors_total")

    def _m2m_stats(self) -> dict:
        """The svc-stats ``m2m`` block (ISSUE 20): live surveillance
        sessions read off their feeds' published progress, finished
        ones from the cumulative fold — `top`'s M2M pane and the
        fleet roll-up consume the same shape."""
        with self._lock:
            out = dict(self._m2m_done)
            jobs = [j for j in self.jobs.values()
                    if j.stream and j.feed is not None
                    and j.state not in TERMINAL_STATES]
        live = 0
        for j in jobs:
            prog = getattr(j.feed, "m2m_progress", None)
            if not isinstance(prog, dict):
                continue
            live += 1
            for k in out:
                if k == "sessions":
                    continue
                try:
                    out[k] += int(prog.get(k, 0) or 0)
                except (TypeError, ValueError):
                    pass
        out["sessions"] += live
        out["active"] = live
        return out

    def _retry_after_s(self) -> float:
        """The queue_full backoff hint: roughly one recent job's wall
        (the deque's maxlen already bounds the window)."""
        walls = list(self._job_walls)
        return round(max(0.5, sum(walls) / len(walls)), 3) if walls \
            else 1.0

    # ---- protocol ------------------------------------------------------
    def _handle_conn(self, conn: socket.socket) -> None:
        if self.tls is not None and conn.family != socket.AF_UNIX:
            # TLS handshake in THIS connection's thread (never the
            # accept loop): a failure — plaintext probe, downgrade,
            # mid-handshake disconnect — is counted and answered
            # with a loud close, and the daemon serves on
            from pwasm_tpu.fleet.transport import server_handshake
            conn = server_handshake(conn, self.tls,
                                    on_failure=self._tls_failed)
            if conn is None:
                return
        protocol.serve_connection(conn, self._dispatch,
                                  peer=_peer_identity(conn),
                                  max_frame_bytes=self.max_frame_bytes)

    def _tls_failed(self, exc: Exception) -> None:
        self.transport_metrics["tls_handshake_failures"].inc()
        self.obs.event("tls_handshake_failed",
                       detail=f"{type(exc).__name__}: {exc}")

    def _auth_label(self, client: str) -> str:
        """Metric label for an auth failure: per-client until the
        universe would explode (identity strings are attacker-
        chosen), then the overflow bucket."""
        if client in self._auth_labels or len(self._auth_labels) < 64:
            self._auth_labels.add(client)
            return client
        return "other"

    def _authorize(self, cmd, req: dict, peer) -> dict | None:
        """The scoped-token gate (ISSUE 19), BEFORE any verb handler
        runs: an unauthorized frame answers `unauthorized` having
        touched no queue/journal/lease state.  None = proceed."""
        from pwasm_tpu.service import authz
        scope = authz.required_scope(cmd, req)
        ok = False
        if scope is None or self.auth.allows(req, peer,
                                             authz.SCOPE_ADMIN):
            ok = True
        elif scope == authz.SCOPE_CANCEL_OWN:
            if self.auth.allows(req, peer, scope):
                job = self.jobs.get(req.get("job_id"))
                # unknown ids fall through to the normal unknown_job
                # answer — the auth layer must not become a job-id
                # oracle; a KNOWN job needs ownership: its recorded
                # fair-share identity == the caller's resolved one
                ok = (job is None or job.client
                      == self._resolve_client(req, peer))
        else:
            ok = self.auth.allows(req, peer, scope)
        key = peer or self._resolve_client(req, peer) or "anonymous"
        if ok:
            self._penalty.clear(key)
            return None
        client = self._resolve_client(req, peer) or "anonymous"
        self.transport_metrics["auth_failures"].inc(
            client=self._auth_label(client))
        self.obs.event("unauthorized", cmd=cmd, client=client)
        # brute-force damping: consecutive failures from this peer
        # earn a capped-exponential hold, served on this connection's
        # own thread — the accept loop and other clients never wait
        time.sleep(self._penalty.fail(key))
        return protocol.err(
            protocol.ERR_UNAUTHORIZED,
            f"cmd {cmd!r} requires scope {scope!r} and the presented "
            "credentials do not grant it (token file: "
            f"{self.auth.path})")

    def _resolve_client(self, req: dict, peer: str | None) -> str:
        """protocol.resolve_client_identity — shared with the fleet
        router so the two bucketings cannot drift."""
        return protocol.resolve_client_identity(req, peer)

    def _dispatch(self, req: dict, peer: str | None = None) -> dict:
        cmd = req.get("cmd")
        if self.auth is not None:
            deny = self._authorize(cmd, req, peer)
            if deny is not None:
                return deny
        if self.rate_limiter is not None \
                and cmd in ("submit", "stream"):
            # per-identity token bucket in FRONT of admission: a
            # refused frame never reaches the queue or the journal,
            # and the hint is the truthful instant the bucket next
            # holds a whole token
            client = self._resolve_client(req, peer)
            wait = self.rate_limiter.admit(client or "default")
            if wait > 0:
                self.obs.event("rate_limited",
                               client=client or "default",
                               retry_after_s=wait)
                return protocol.err(
                    protocol.ERR_OVERLOADED,
                    f"rate limit: client "
                    f"{client or 'default'} exceeded "
                    f"{self.rate_limiter.rate:g}/s "
                    f"(burst {self.rate_limiter.burst:g})",
                    client=client or "default",
                    retry_after_s=wait)
        # eviction runs on every request (plus the accept-loop tick
        # and each admission), so reads observe a deterministic
        # post-eviction view: an id past its TTL/LRU budget answers
        # unknown_job on the very next request, not a tick later
        self._evict_results()
        if cmd == "ping":
            return protocol.ok(
                protocol_version=protocol.PROTOCOL_VERSION,
                draining=self._draining)
        if cmd in ("submit", "stream", "stream-data") \
                and self.epoch_lease.fenced:
            # the fence: no NEW work while the lease is lost — the
            # fleet may already have handed our jobs to siblings.
            # Reads (status/result), stream-end, cancel, stats (the
            # lease heartbeat rides it) and drain all still serve.
            return protocol.err(
                protocol.ERR_FENCED,
                "member is fenced (lost its fleet epoch lease): "
                "new work refused until the router re-grants a "
                "lease — submit to the fleet router instead",
                epoch=self.epoch_lease.epoch)
        if cmd == "lease-grant":
            ok, detail = self._lease_grant(
                {"epoch": req.get("epoch"),
                 "ttl_s": req.get("ttl_s")})
            if not ok:
                return protocol.err(
                    protocol.ERR_FENCED, detail,
                    lease=self.epoch_lease.as_dict())
            return protocol.ok(lease=self.epoch_lease.as_dict())
        if cmd == "fence":
            self._fence(str(req.get("reason")
                            or "fence requested by client"))
            return protocol.ok(lease=self.epoch_lease.as_dict())
        if cmd == "submit":
            client = self._resolve_client(req, peer)
            deadline_ms, dl_err = protocol.parse_deadline_ms(req)
            if dl_err is not None:
                return dl_err
            try:
                job = self.submit(req.get("args"),
                                  cwd=req.get("cwd"),
                                  client=client,
                                  priority=req.get("priority"),
                                  trace_id=req.get("trace_id"),
                                  deadline_ms=deadline_ms)
            except ValueError as e:
                return protocol.err(protocol.ERR_BAD_REQUEST, str(e))
            except Draining as e:
                self.stats.jobs_rejected_draining += 1
                self.svc_metrics["jobs"].inc(
                    outcome="rejected_draining")
                return protocol.err(protocol.ERR_DRAINING, str(e))
            except QueueFull as e:
                # the 429: a well-behaved client backs off and retries
                # (`submit --retry` honors retry_after_s with capped-
                # exponential backoff).  The quota is per client, so
                # the frame names WHOSE quota filled.
                self.stats.jobs_rejected += 1
                self.svc_metrics["jobs"].inc(outcome="rejected")
                return protocol.err(
                    protocol.ERR_QUEUE_FULL, str(e),
                    queue_depth=self.queue.depth(),
                    max_queue=self.queue.max_queue,
                    client=client or "default",
                    client_depth=self.queue.client_depths().get(
                        client, 0),
                    retry_after_s=self._retry_after_s())
            return protocol.ok(job_id=job.id,
                               trace_id=job.trace_id,
                               queue_depth=self.queue.depth())
        if cmd == "stream":
            # streaming ingestion (ISSUE 10): admit a job whose PAF
            # records will arrive as stream-data frames — the
            # minimap2-pipe-over-the-socket shape.  Admission control
            # is the same per-client fair-share gate as submit.
            client = self._resolve_client(req, peer)
            deadline_ms, dl_err = protocol.parse_deadline_ms(req)
            if dl_err is not None:
                return dl_err
            try:
                job = self.submit(req.get("args"),
                                  cwd=req.get("cwd"),
                                  client=client,
                                  priority=req.get("priority"),
                                  stream=True,
                                  trace_id=req.get("trace_id"),
                                  deadline_ms=deadline_ms,
                                  delta=bool(req.get("delta")))
            except ValueError as e:
                return protocol.err(protocol.ERR_BAD_REQUEST, str(e))
            except Draining as e:
                self.stats.jobs_rejected_draining += 1
                self.svc_metrics["jobs"].inc(
                    outcome="rejected_draining")
                return protocol.err(protocol.ERR_DRAINING, str(e))
            except QueueFull as e:
                self.stats.jobs_rejected += 1
                self.svc_metrics["jobs"].inc(outcome="rejected")
                return protocol.err(
                    protocol.ERR_QUEUE_FULL, str(e),
                    queue_depth=self.queue.depth(),
                    max_queue=self.queue.max_queue,
                    client=client or "default",
                    retry_after_s=self._retry_after_s())
            return protocol.ok(job_id=job.id,
                               trace_id=job.trace_id,
                               max_buffer=self.streams.max_buffer,
                               queue_depth=self.queue.depth())
        if cmd in ("stream-data", "stream-end"):
            job = self.jobs.get(req.get("job_id"))
            if job is None:
                return protocol.err(
                    protocol.ERR_UNKNOWN_JOB,
                    f"unknown job_id {req.get('job_id')!r}")
            if not job.stream:
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    f"job {job.id} is not a stream job")
            job.accessed_s = time.time()
            feed = job.feed
            closed = (feed is None or job.state in TERMINAL_STATES
                      or (cmd == "stream-data" and feed.ended))
            if closed and cmd == "stream-data":
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    f"stream {job.id} is closed ({job.state})"
                    + ("; re-open a stream with --resume to complete "
                       "it" if job.state == JOB_PREEMPTED else ""))
            if cmd == "stream-end":
                ds = job.dstate
                if ds is not None and ds.get("mode") == "holding" \
                        and feed is not None and not feed.ended:
                    return self._delta_stream_finish(job)
                if feed is not None:
                    if ds is not None \
                            and ds.get("mode") == "resolved" \
                            and not feed.ended:
                        from pwasm_tpu.service.cache import \
                            line_digest
                        for tail in ds["asm"].flush():
                            ds["digests"].append(line_digest(tail))
                    feed.end()
                return protocol.ok(
                    records=feed.records_in if feed else 0,
                    buffered=feed.buffered if feed else 0)
            data = req.get("data")
            if not isinstance(data, str):
                return protocol.err(
                    protocol.ERR_BAD_REQUEST,
                    "stream-data needs a string data field")
            ds = job.dstate
            if ds is not None and ds.get("mode") == "holding":
                # delta hold (ROADMAP 4c): this frame is digested and
                # parked/promoted instead of fed — the job is not in
                # the queue yet
                return self._delta_stream_data(job, req, data)
            n = feed.completed(data)
            if not n and data:
                # the record quota counts complete lines, so
                # newline-less frames must be bounded separately or
                # one client grows the partial-record tail without
                # limit (a protocol violation, not backpressure — no
                # resend can help, so the error is NOT queue_full)
                from pwasm_tpu.stream.pafstream import \
                    MAX_RECORD_BYTES
                if feed.tail_bytes + len(data) > MAX_RECORD_BYTES:
                    return protocol.err(
                        protocol.ERR_BAD_REQUEST,
                        f"unterminated PAF record exceeds "
                        f"{MAX_RECORD_BYTES} bytes — stream-data "
                        "frames must eventually carry a newline")
            if n:
                try:
                    # all-or-nothing per frame: a rejected frame left
                    # no assembler state behind and resends verbatim
                    self.streams.admit(job.id, n)
                except QueueFull as e:
                    # the streaming 429: back off (retry_backoff_s)
                    # and resend — the executing job is draining the
                    # buffer at device speed, so the hint is short
                    return protocol.err(
                        protocol.ERR_QUEUE_FULL, str(e),
                        buffered=feed.buffered,
                        max_buffer=self.streams.max_buffer,
                        retry_after_s=0.1)
            fed = feed.feed(data)
            if fed:
                self.stream_metrics["records"].inc(
                    fed, client=job.client or "default")
            if ds is not None and ds.get("mode") == "resolved":
                # keep the digest mirror current for the finish-time
                # insert — AFTER the commit, so a rejected frame's
                # verbatim resend cannot double-digest
                from pwasm_tpu.service.cache import line_digest
                for ln in ds["asm"].push(data):
                    ds["digests"].append(line_digest(ln))
            return protocol.ok(buffered=feed.buffered,
                               records=feed.records_in)
        if cmd == "stats":
            # queue depth / in-flight / breaker state read back from
            # the SAME registry gauges the `metrics` exposition serves
            # — the two operator surfaces cannot drift (ISSUE 6)
            self._refresh_gauges()
            m = self.svc_metrics
            st = self.stats.as_dict(
                queue_depth=int(m["queue_depth"].value()),
                running=int(m["inflight"].value()),
                draining=self._draining,
                max_queue=self.queue.max_queue,
                max_concurrent=self.max_concurrent,
                breaker_state=int(m["breaker_state"].value()))
            # additive (stats_version unchanged): the device-lease
            # lane table — span, busy, per-lane breaker — plus the
            # grant/wait roll-up
            st["lanes"] = self.leases.lane_states()
            st["leases"] = {
                "lanes": self.leases.n_lanes,
                "devices_per_job": self.devices_per_job,
                "busy": self.leases.busy_count(),
                "waiting": self.leases.waiting_count(),
                "grants": self.leases.grants,
                "wait_s_total": round(self.leases.wait_s_total, 6),
            }
            # additive (stats_version unchanged): crash-safety +
            # fair-share surfaces (ISSUE 9)
            st["fair_share"] = {
                "max_queue_per_client": self.queue.max_queue,
                "max_queue_total": self.queue.max_total,
                "priority_lanes": [l for l in
                                   self.queue.priority_lanes if l],
                "clients": {(c or "default"): n for c, n in
                            self.queue.client_depths().items()},
            }
            st["journal"] = {
                "path": self.journal.path if self.journal else None,
                "records": (self.journal.records_written
                            if self.journal else 0),
                "broken": (self.journal.broken is not None
                           if self.journal else False),
                "replays": self.stats.journal_replays,
                "jobs_recovered": self.stats.jobs_recovered,
            }
            st["spool"] = {
                "dir": self.spool_dir,
                "threshold_bytes": self.spool_threshold_bytes,
                "bytes": self.ledger.value("spool"),
            }
            # additive (stats_version unchanged): the result cache
            # (ISSUE 15) — hit/miss flow, on-disk bytes, hit ratio
            st["cache"] = self.cache.stats_dict() \
                if self.cache is not None else {"enabled": False}
            # additive (stats_version unchanged): streaming ingestion
            # (ISSUE 10) — live streams, record/batch flow, buffer lag
            tot = self.streams.totals()
            st["streams"] = {
                "active": tot["active"],
                "records_in": tot["records_in"],
                "records_out": tot["records_out"],
                "batches": tot["batches"],
                "lag_records": tot["buffered"],
                "max_buffer": self.streams.max_buffer,
                "max_buffer_total": self.streams.max_total,
            }
            # additive (stats_version unchanged): continuous
            # surveillance m2m sessions (ISSUE 20) — arrival/dispatch
            # flow, incremental reuse, section emission
            st["m2m"] = self._m2m_stats()
            # additive (stats_version unchanged): the self-monitoring
            # verdict (ISSUE 14) — `top`'s alerts pane reads it from
            # the same surface as the JSON verbs
            st["health"] = self._health()
            # additive: epoch-lease fencing (ISSUE 16).  The router's
            # lease heartbeat RIDES the stats poll (req["lease"]), so
            # governance costs zero extra RPCs; the reply always
            # carries the member's lease view (+ the grant verdict
            # when one was attempted)
            lease_req = req.get("lease")
            lb = self.epoch_lease.as_dict()
            if lease_req is not None:
                ok_g, detail = self._lease_grant(lease_req)
                lb = self.epoch_lease.as_dict()
                lb["accepted"] = ok_g
                if not ok_g:
                    lb["refused_detail"] = detail
            st["lease"] = lb
            return protocol.ok(stats=st)
        if cmd == "metrics":
            self._refresh_gauges()
            # exemplars are OPT-IN (frame field / `metrics
            # --exemplars`): the default body stays parseable by
            # strict 0.0.4 scrapers
            return protocol.ok(
                metrics=self.registry.expose(
                    exemplars=bool(req.get("exemplars"))),
                content_type="text/plain; version=0.0.4")
        if cmd == "health":
            # the machine-readable health verdict (ISSUE 14):
            # ok/degraded/failing + the firing rules + canary state —
            # what `pwasm-tpu health --exit-code` and any external
            # orchestrator probe consume
            return protocol.ok(health=self._health())
        if cmd == "cache-probe":
            # fleet cache affinity (ISSUE 15): the router asks whether
            # this member could answer a key from its result cache —
            # a cheap manifest check, no blob reads, no admission
            key = req.get("key")
            if not isinstance(key, str) or not key:
                return protocol.err(protocol.ERR_BAD_REQUEST,
                                    "cache-probe needs a key field")
            fam = req.get("family")
            return protocol.ok(
                enabled=self.cache is not None,
                hit=self.cache is not None
                and self.cache.contains(key),
                # delta affinity (ISSUE 17c): true when an entry of
                # the job's FAMILY is held — this member could answer
                # the near-repeat as an admission delta
                family_hit=self.cache is not None
                and isinstance(fam, str) and bool(fam)
                and self.cache.contains_family(fam))
        if cmd == "logs":
            # the incident-query verb (ISSUE 14 satellite): filter
            # THIS daemon's --log-json (rotated .1 generation
            # included) by trace_id/job/event — the same query
            # `pwasm-tpu logs FILE` runs locally
            return protocol.handle_logs(req, self.log_json_path)
        if cmd == "drain":
            self.drain.request("drain requested by client")
            self._begin_drain(self.drain.reason)
            with self._lock:
                # snapshot under the lock: a concurrent submit mutates
                # self.jobs, and iterating it bare would raise mid-
                # drain (answering bad_request for a drain that DID
                # latch)
                running = sorted(self._running)
                preempted = sorted(
                    j.id for j in self.jobs.values()
                    if j.state == JOB_PREEMPTED
                    and j.started_s is None)
            return protocol.ok(draining=True, running=running,
                               preempted_queued=preempted)
        if cmd in ("status", "result", "cancel", "inspect"):
            job = self.jobs.get(req.get("job_id"))
            if job is None:
                # unknown OR evicted (--result-ttl-s/--max-results):
                # indistinguishable by design
                return protocol.err(
                    protocol.ERR_UNKNOWN_JOB,
                    f"unknown job_id {req.get('job_id')!r}")
            job.accessed_s = time.time()   # the LRU clock
            if cmd == "inspect":
                # the flight record (ISSUE 11): phase-accounted walls
                # + the event ring — from RAM while the job holds it,
                # from the CRC-verified spool once the result moved
                # to disk
                flight = None
                spool_error = None
                if job.spool is not None:
                    obj, spool_error = self._load_spool(job)
                    flight = obj.get("flight") if obj else None
                elif job.flight is not None:
                    wall = ((job.finished_s or time.time())
                            - job.submitted_s)
                    flight = job.flight.summary(wall_s=wall)
                resp = protocol.ok(job=job.describe(),
                                   trace_id=job.trace_id,
                                   flight=flight)
                if spool_error is not None:
                    resp["spool_error"] = spool_error
                return resp
            if cmd == "status":
                return protocol.ok(job=job.describe(),
                                   queue_depth=self.queue.depth())
            if cmd == "result":
                if req.get("wait", True):
                    job.done.wait(req.get("timeout"))
                d = job.describe()
                if job.state not in TERMINAL_STATES:
                    return protocol.ok(job=d, pending=True)
                stats, tail = job.stats, job.stderr_tail
                spool_error = None
                if job.spool is not None:
                    # disk-spooled result: RAM held only the index —
                    # the frame streams from the spool file on demand
                    obj, spool_error = self._load_spool(job)
                    stats = obj.get("stats") if obj else None
                    tail = str(obj.get("stderr_tail") or "") \
                        if obj else ""
                resp = protocol.ok(job=d, rc=job.rc, stats=stats,
                                   stderr_tail=tail)
                if spool_error is not None:
                    resp["spool_error"] = spool_error
                return resp
            return self._cancel(job)
        return protocol.err(protocol.ERR_UNKNOWN_CMD,
                            f"unknown cmd {cmd!r}")

    def _cancel(self, job: Job) -> dict:
        if job.state == JOB_QUEUED and job.dstate is not None \
                and job.dstate.get("mode") == "holding":
            # a delta-HELD stream is not in the queue (queue.remove
            # below would miss it and the running branch would wait
            # forever on a job that never starts): retire it directly
            job.dstate["mode"] = "off"
            self._retire_stream(job)
            job.state = JOB_CANCELLED
            job.rc = None
            job.detail = ("cancelled while held for stream-delta "
                          "classification (never started)")
            job.finished_s = time.time()
            self.stats.jobs_cancelled += 1
            self.svc_metrics["jobs"].inc(outcome="cancelled")
            self._journal_append(REC_FINISH, job_id=job.id,
                                 state=JOB_CANCELLED, rc=None,
                                 detail=job.detail)
            self.obs.event("job_cancel", job_id=job.id, was="held",
                           trace_id=job.trace_id)
            job.done.set()
            return protocol.ok(state=JOB_CANCELLED, was="held")
        if job.state == JOB_QUEUED and self.queue.remove(job):
            self._retire_stream(job)
            job.state = JOB_CANCELLED
            job.rc = None
            job.detail = "cancelled while queued (never started)"
            job.finished_s = time.time()
            self.stats.jobs_cancelled += 1
            self.svc_metrics["jobs"].inc(outcome="cancelled")
            self._journal_append(REC_FINISH, job_id=job.id,
                                 state=JOB_CANCELLED, rc=None,
                                 detail=job.detail)
            self.obs.event("job_cancel", job_id=job.id, was="queued",
                           trace_id=job.trace_id)
            job.done.set()
            return protocol.ok(state=JOB_CANCELLED, was="queued")
        if job.state in TERMINAL_STATES:
            return protocol.ok(state=job.state, was="terminal")
        # running — or QUEUED-but-already-dequeued (the worker holds
        # it between take() and the RUNNING transition, so the queue
        # removal above missed): a per-job graceful drain either way.
        # The job stops at its next batch boundary with a valid
        # checkpoint — a mid-batch kill would only throw away
        # finished work, and the pre-armed drain flag catches the
        # about-to-run case at its first boundary.
        job.cancel_requested = True
        if job.drain is not None:
            job.drain.request("cancelled by client")
        # journaled so a crash mid-cancel cannot silently UN-cancel:
        # replay lands the job terminal-cancelled instead of re-running
        self._journal_append(REC_CANCEL, job_id=job.id)
        self.obs.event("job_cancel", job_id=job.id, was="running",
                       trace_id=job.trace_id)
        return protocol.ok(state="cancelling", was="running")


def load_spool_payload(path: str):
    """(payload, error) from a spooled-result file, CRC-verified (the
    ckpt-v2 rule: a torn or rotted spool is reported unreadable, never
    served as if whole).  Module-level because the fleet router reads
    a DEAD member's spool files during journal-aware failover — same
    verification, different process."""
    import json

    from pwasm_tpu.utils.fsio import payload_crc
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
        if not isinstance(obj, dict):
            raise ValueError("not an object")
        crc = int(obj.pop("crc"))
        if payload_crc(obj) != crc:
            raise ValueError("spool payload CRC mismatch")
        return obj, None
    except (OSError, ValueError, KeyError, TypeError) as e:
        return None, f"spooled result unreadable ({e})"


# the argv slots that hold PATHS, resolved against the client's cwd:
# short value flags (from cli._VALUE_FLAGS; -c is clipmax, -d/-p/-m are
# the reference's parsed-but-unread quirks), --long=FILE options, and
# the positional PAF input.
_PATH_SHORT = frozenset("rows")
_PATH_LONG = frozenset(("stats", "profile", "motifs",
                        "ace", "info", "cons",
                        "trace-json", "log-json", "metrics-textfile"))


def _absolutize_argv(argv: list[str], cwd: str) -> list[str]:
    """Rewrite relative paths in a job argv against the CLIENT's
    ``cwd``, walking tokens with the same grammar as
    ``cli._parse_args`` (clustered short flags, joined or separated
    values, ``--long=value``) so the rewrite cannot disagree with what
    the run will parse.  Unknown flags pass through untouched — the
    submit-time validation rejects the argv right after with the CLI's
    own diagnostic."""
    from pwasm_tpu.cli import _BOOL_FLAGS, _VALUE_FLAGS

    def ab(v: str) -> str:
        # "-" is the conventional stdin marker, not a path
        if not v or v == "-" or os.path.isabs(v):
            return v
        return os.path.join(cwd, v)

    out: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            if "=" in a:
                k, v = a[2:].split("=", 1)
                if k in _PATH_LONG:
                    a = f"--{k}={ab(v)}"
            out.append(a)
        elif a.startswith("-") and len(a) > 1:
            j = 1
            rebuilt = "-"
            value_flag = None      # set when the flag's value is the
            #                        NEXT argv token
            while j < len(a):
                ch = a[j]
                if ch in _BOOL_FLAGS:
                    rebuilt += ch
                    j += 1
                elif ch in _VALUE_FLAGS:
                    rebuilt += ch
                    if j + 1 < len(a):     # joined value: -oFILE
                        v = a[j + 1:]
                        rebuilt += ab(v) if ch in _PATH_SHORT else v
                    else:
                        value_flag = ch
                    j = len(a)
                else:
                    rebuilt = a            # unknown flag: untouched
                    j = len(a)
            out.append(rebuilt)
            if value_flag is not None and i + 1 < len(argv):
                i += 1
                v = argv[i]
                out.append(ab(v) if value_flag in _PATH_SHORT else v)
        else:
            out.append(ab(a))              # positional: the PAF input
        i += 1
    return out


def _peer_identity(conn: socket.socket) -> str | None:
    """The connection's DEFAULT fair-share identity, attested by the
    transport: an mTLS client certificate's CN (``cn:<name>`` — the
    listener verified the chain against --tls-client-ca, so the name
    is as trustworthy as the CA), else the unix-socket peer uid via
    ``SO_PEERCRED`` (kernel-attested — a client cannot spoof it the
    way a free-form field could), rendered ``uid:<n>``.  An explicit
    ``client=`` submit field overrides it: one uid fronting many
    logical tenants (a scheduler submitting for users) needs the
    finer identity, and admission quotas are a fairness device here,
    not a security boundary.  None when the platform has no peer
    credentials — those submits share the anonymous bucket."""
    from pwasm_tpu.fleet.transport import peer_common_name
    cn = peer_common_name(conn)
    if cn:
        return f"cn:{cn}"
    peercred = getattr(socket, "SO_PEERCRED", None)
    if peercred is None:
        return None
    if conn.family != socket.AF_UNIX:
        # a TCP peer has no kernel credential (Linux answers uid -1
        # rather than failing): identity there is the explicit
        # client_token, never a fake attestation
        return None
    try:
        import struct
        raw = conn.getsockopt(socket.SOL_SOCKET, peercred,
                              struct.calcsize("3i"))
        _pid, uid, _gid = struct.unpack("3i", raw)
        return f"uid:{uid}"
    except (OSError, ValueError):
        return None


def _socket_alive(path: str) -> bool:
    # kept as an alias: the probe itself moved to fleet/transport.py
    # (the single socket factory the find_tls_violations gate allows)
    from pwasm_tpu.fleet.transport import socket_alive
    return socket_alive(path)


def serve_main(argv: list[str], stdout=None, stderr=None) -> int:
    """The ``pwasm-tpu serve`` entry point."""
    stderr = stderr if stderr is not None else sys.stderr
    opts: dict[str, str] = {}
    for a in argv:
        if a.startswith("--") and "=" in a:
            k, v = a[2:].split("=", 1)
            opts[k] = v
        elif a == "--warmup":
            opts["warmup"] = "tpu"   # bare form: warm the device path
        elif a == "--cache-prefetch":
            opts["cache-prefetch"] = "64"   # bare form: default depth
        elif a in ("-h", "--help"):
            stderr.write(_SERVE_USAGE)
            return EXIT_USAGE
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid argument: {a}\n")
            return EXIT_USAGE
    sock = opts.pop("socket", None)
    if not sock:
        stderr.write(f"{_SERVE_USAGE}\nError: --socket=PATH is "
                     "required\n")
        return EXIT_USAGE
    nums = {}
    for knob, dflt in (("max-queue", 16), ("max-concurrent", 1),
                       ("max-frame-bytes", protocol.MAX_FRAME_BYTES),
                       ("devices-per-job", 1), ("lanes", None),
                       ("max-queue-total", None),
                       ("spool-threshold-bytes", None),
                       ("stream-buffer", 512),
                       ("log-json-max-bytes", None),
                       ("result-cache-max-bytes", None)):
        val = opts.pop(knob, None)
        if val is None:
            nums[knob] = dflt
        elif val.isascii() and val.isdigit() and int(val) >= 1:
            nums[knob] = int(val)
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid --{knob} value: "
                         f"{val}\n")
            return EXIT_USAGE
    journal_path = opts.pop("journal", "auto")
    if journal_path == "off":
        journal_path = None
    elif journal_path is not None and journal_path != "auto" \
            and not journal_path.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --journal value\n")
        return EXIT_USAGE
    listen = opts.pop("listen", None)
    if listen is not None:
        from pwasm_tpu.fleet.transport import is_tcp_target
        if not is_tcp_target(listen):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --listen value: "
                         f"{listen} (HOST:PORT)\n")
            return EXIT_USAGE
    journal_dir = opts.pop("journal-dir", None)
    if journal_dir is not None and not journal_dir.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --journal-dir value\n")
        return EXIT_USAGE
    if journal_dir is not None and journal_path != "auto":
        # an explicit --journal=PATH would silently defeat the shared
        # placement a router's --journal-dir computes (it would look
        # for DIR/<member-name>.journal the member never writes, and
        # failover would lose every journal verdict) — refuse the
        # half-applied combination
        stderr.write(f"{_SERVE_USAGE}\nError: --journal-dir and an "
                     "explicit --journal are mutually exclusive "
                     "(the dir DERIVES the journal path so the "
                     "fleet router can find it)\n")
        return EXIT_USAGE
    compile_cache_dir = opts.pop("compile-cache-dir", None)
    if compile_cache_dir is not None and not compile_cache_dir.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --compile-cache-dir "
                     "value\n")
        return EXIT_USAGE
    warmup = None
    if "warmup" in opts:
        warmup = opts.pop("warmup")
        if warmup not in ("tpu", "cpu"):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --warmup value: "
                         f"{warmup} (tpu or cpu)\n")
            return EXIT_USAGE
    spool_dir = opts.pop("spool-dir", None)
    if spool_dir is not None and not spool_dir.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --spool-dir value\n")
        return EXIT_USAGE
    result_cache = opts.pop("result-cache", None)
    if result_cache is not None and not result_cache.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --result-cache "
                     "value\n")
        return EXIT_USAGE
    if result_cache == "off":
        result_cache = None
    cache_prefetch = None
    val = opts.pop("cache-prefetch", None)
    if val is not None:
        if val.isascii() and val.isdigit() and int(val) >= 1:
            cache_prefetch = int(val)
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid --cache-prefetch "
                         f"value: {val}\n")
            return EXIT_USAGE
    priority_lanes: tuple[str, ...] | None = None
    val = opts.pop("priority-lanes", None)
    if val is not None:
        lanes = [l.strip() for l in val.split(",")]
        if (not lanes or any(not l or not _CLIENT_RE.match(l)
                             for l in lanes)
                or len(set(lanes)) != len(lanes)):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --priority-lanes "
                         f"value: {val} (comma-separated unique "
                         "names, highest first)\n")
            return EXIT_USAGE
        priority_lanes = tuple(lanes)
    stream_idle_s = 300.0
    val = opts.pop("stream-idle-s", None)
    if val is not None:
        import math
        try:
            stream_idle_s = float(val)
            if stream_idle_s <= 0 or not math.isfinite(stream_idle_s):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --stream-idle-s "
                         f"value: {val}\n")
            return EXIT_USAGE
    canary_interval_s = None
    val = opts.pop("canary-interval", None)
    if val is not None:
        import math
        try:
            canary_interval_s = float(val)
            if canary_interval_s <= 0 \
                    or not math.isfinite(canary_interval_s):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --canary-interval "
                         f"value: {val}\n")
            return EXIT_USAGE
    slo_rules = None
    val = opts.pop("slo-rules", None)
    if val is not None:
        if val == "off":
            slo_rules = "off"
        else:
            from pwasm_tpu.obs.slo import load_rules_file
            try:
                slo_rules = load_rules_file(val)
            except ValueError as e:
                stderr.write(f"{_SERVE_USAGE}\nError: {e}\n")
                return EXIT_USAGE
    # zero-trust edge (ISSUE 19): TLS/mTLS on the TCP listener,
    # scoped capability tokens, per-identity rate limiting — each
    # strictly opt-in
    tls_cert = opts.pop("tls-cert", None)
    tls_key = opts.pop("tls-key", None)
    tls_client_ca = opts.pop("tls-client-ca", None)
    if (tls_cert is None) != (tls_key is None):
        stderr.write(f"{_SERVE_USAGE}\nError: --tls-cert and "
                     "--tls-key must be given together\n")
        return EXIT_USAGE
    if tls_client_ca is not None and tls_cert is None:
        stderr.write(f"{_SERVE_USAGE}\nError: --tls-client-ca "
                     "requires --tls-cert/--tls-key\n")
        return EXIT_USAGE
    tls = None
    if tls_cert is not None:
        from pwasm_tpu.fleet.transport import ServerTLS
        try:
            tls = ServerTLS(tls_cert, tls_key,
                            client_ca=tls_client_ca)
        except ValueError as e:
            stderr.write(f"Error: {e}\n")
            return EXIT_USAGE
    auth_tokens = opts.pop("auth-tokens", None)
    if auth_tokens is not None and not auth_tokens.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --auth-tokens value\n")
        return EXIT_USAGE
    rate_limit = None
    val = opts.pop("rate-limit", None)
    if val is not None:
        from pwasm_tpu.service.queue import parse_rate_limit
        try:
            rate_limit = parse_rate_limit(val)
        except ValueError as e:
            stderr.write(f"{_SERVE_USAGE}\nInvalid --rate-limit "
                         f"value: {val} ({e})\n")
            return EXIT_USAGE
    metrics_textfile = opts.pop("metrics-textfile", None)
    log_json = opts.pop("log-json", None)
    trace_json = opts.pop("trace-json", None)
    if trace_json is not None and not trace_json.strip():
        stderr.write(f"{_SERVE_USAGE}\nInvalid --trace-json value\n")
        return EXIT_USAGE
    result_ttl_s = None
    val = opts.pop("result-ttl-s", None)
    if val is not None:
        import math
        try:
            result_ttl_s = float(val)
            if result_ttl_s < 0 or not math.isfinite(result_ttl_s):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_SERVE_USAGE}\nInvalid --result-ttl-s "
                         f"value: {val}\n")
            return EXIT_USAGE
    max_results = None
    val = opts.pop("max-results", None)
    if val is not None:
        if val.isascii() and val.isdigit():
            max_results = int(val)
        else:
            stderr.write(f"{_SERVE_USAGE}\nInvalid --max-results "
                         f"value: {val}\n")
            return EXIT_USAGE
    if opts:
        stderr.write(f"{_SERVE_USAGE}\nInvalid argument: "
                     f"--{next(iter(opts))}\n")
        return EXIT_USAGE
    try:
        daemon = Daemon(sock, max_queue=nums["max-queue"],
                        max_concurrent=nums["max-concurrent"],
                        max_frame_bytes=nums["max-frame-bytes"],
                        stderr=stderr,
                        metrics_textfile=metrics_textfile,
                        log_json=log_json, result_ttl_s=result_ttl_s,
                        max_results=max_results,
                        lanes=nums["lanes"],
                        devices_per_job=nums["devices-per-job"],
                        journal_path=journal_path,
                        max_queue_total=nums["max-queue-total"],
                        priority_lanes=priority_lanes,
                        spool_threshold_bytes=nums[
                            "spool-threshold-bytes"],
                        spool_dir=spool_dir,
                        stream_buffer=nums["stream-buffer"],
                        stream_idle_s=stream_idle_s,
                        log_json_max_bytes=nums["log-json-max-bytes"],
                        trace_json=trace_json,
                        listen=listen, journal_dir=journal_dir,
                        compile_cache_dir=compile_cache_dir,
                        warmup=warmup,
                        canary_interval_s=canary_interval_s,
                        slo_rules=slo_rules,
                        result_cache=result_cache,
                        result_cache_max_bytes=nums[
                            "result-cache-max-bytes"],
                        cache_prefetch=cache_prefetch,
                        tls=tls, auth_tokens=auth_tokens,
                        rate_limit=rate_limit)
    except ValueError as e:
        # fail-fast --auth-tokens load: never come up OPEN because
        # the policy file was bad
        stderr.write(f"Error: {e}\n")
        return EXIT_USAGE
    except OSError:
        stderr.write(f"Cannot open file {log_json} for writing!\n")
        return EXIT_USAGE
    try:
        return daemon.serve()
    except PwasmError as e:
        stderr.write(str(e))
        return e.exit_code
