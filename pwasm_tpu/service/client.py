"""Client side of the warm-pool service (``pwasm-tpu submit`` /
``pwasm-tpu svc-stats``) and the :class:`ServiceClient` library the
bench, QA drills and tests drive.

A client is one unix-socket connection speaking the newline-delimited
JSON protocol (``service.protocol``).  ``submit`` is the cold-CLI
drop-in: the job argv after ``--`` (or after the client flags) is
exactly what a cold ``python -m pwasm_tpu.cli`` invocation would take,
and the client's exit code is the job's exit code — so a fleet wrapper
can switch between cold runs and warm submissions by prefixing
``submit --socket=PATH --`` and nothing else changes.
"""

from __future__ import annotations

import json
import socket
import sys
import time

from pwasm_tpu.core.errors import EXIT_FATAL, EXIT_USAGE
from pwasm_tpu.service import protocol

_CLIENT_USAGE = """Usage:
 pwasm-tpu submit --socket=TARGET [--no-wait] [--timeout=S]
                  [--retry[=N]] [--client=NAME] [--priority=LANE]
                  [--client-token=TOK] [--deadline-s=S]
                  [--] <cli args...>

 TARGET is a unix socket path or a HOST:PORT TCP endpoint (a `serve
 --listen` daemon or a `route` fleet router — docs/FLEET.md).  On TCP
 there is no kernel peer credential, so pass --client-token=TOK to
 claim a fair-share identity (jobs bucket under tok:TOK); untokened
 TCP submits share the anonymous bucket.
     submit one report job (the argv a cold CLI run would take; -o is
     required — the socket carries control, not report bytes).  By
     default waits for the job and exits with the JOB's exit code
     (0 done, 75 preempted/cancelled-resumable, else failed); with
     --no-wait prints the job id and exits 0.  A full queue
     (queue_full) exits 11 so wrappers can back off and retry — or
     pass --retry[=N] (default 5 attempts) and the client backs off
     ITSELF: capped-exponential waits seeded by the daemon's
     retry_after_s hint, exiting 11 only once the budget is spent.
     --client=NAME overrides the fair-share identity (default: the
     socket-peer uid); --priority=LANE targets a --priority-lanes
     tier on the daemon.
     --deadline-s=S arms an END-TO-END deadline: every frame carries
     the remaining budget (deadline_ms), each hop subtracts the time
     it spent (router queue/spill, daemon queue + lease wait), and a
     job that cannot finish in budget stops at its next batch
     boundary with a valid resumable checkpoint and a
     deadline_exceeded verdict (rc 75 — resume it with a fresh
     budget, or don't).  The verdict JSON shows the budget
     arithmetic (docs/RESILIENCE.md).

 pwasm-tpu stream --socket=PATH [--timeout=S] [--client=NAME]
                  [--priority=LANE] [--deadline-s=S]
                  [--] <cli args...>
     open a STREAM job (docs/STREAMING.md) and feed it the PAF read
     from stdin, record-at-a-time — `minimap2 --cs ... | pwasm-tpu
     stream --socket=S -- -r cds.fa -o out.dfa` is the pipe shape.
     The job argv takes no positional PAF (records arrive over the
     socket); -o is required like submit.  Backpressure (queue_full
     mid-stream) is handled with capped-exponential backoff
     automatically; exits with the job's exit code.

 pwasm-tpu svc-stats --socket=PATH [--drain]
     print the service-level stats JSON (versioned schema); with
     --drain, ask the daemon to drain gracefully first (running jobs
     finish at batch boundaries, queued jobs report resumable, daemon
     exits 75).

 pwasm-tpu metrics --socket=PATH [--exemplars]
     print the daemon's metrics as Prometheus text exposition (queue
     depth, in-flight jobs, breaker state, job wall/queue-wait
     histograms, cumulative per-run counters) — the socket twin of
     `serve --metrics-textfile=PATH` (docs/OBSERVABILITY.md).  With
     --exemplars, histogram buckets carry the OpenMetrics exemplar
     suffix linking each bucket to a trace_id (strict 0.0.4 parsers
     reject it, so the default stays pure).

 pwasm-tpu inspect --socket=PATH JOB_ID
     print the job's FLIGHT RECORD as JSON (docs/OBSERVABILITY.md):
     trace_id, phase-accounted walls (queue wait, lease wait, exec —
     with the run's per-flush device/host/format breakdown inside)
     and the bounded event ring (retries, breaker transitions, OOM
     bisections, ckpt writes).  Works on live, finished, and
     disk-spooled jobs (spooled records are CRC-verified).

 pwasm-tpu health --socket=TARGET [--exit-code]
     print the daemon's (or, against a router, the FLEET's) health
     verdict as JSON: ok/degraded/failing, the firing SLO rules
     (docs/OBSERVABILITY.md rule catalog), canary state, and — on a
     router — every member's folded verdict.  With --exit-code the
     shell exit encodes the verdict (0 ok, 1 degraded, 2 failing) —
     the orchestrator-probe form (k8s liveness, cron pagers).

 pwasm-tpu logs (--socket=TARGET | FILE) [--trace-id=ID] [--job=ID]
                [--event=TYPE] [--limit=N]
     query the NDJSON event log — a live daemon/router's --log-json
     over the socket, or a log FILE on disk directly — filtered by
     trace_id (matches run_id too), job id, and/or event type,
     rotated .1 generation included, newest --limit (default 1000)
     matches in order.  Incident reconstruction without hand-grepping
     two files.

 Every frame this client sends carries a trace_id (minted per
 connection, or --trace-id=ID to join an existing trace): the daemon
 stamps it into its journal, event log, flight record and trace spans
 — one greppable identity for a job across both processes.
 `submit --trace-json=FILE` / `stream --trace-json=FILE` additionally
 record the CLIENT's side (submit RPC / stream feed, result wait) as
 a wall-anchored Chrome trace — written on error paths too, because a
 daemon that died mid-job is exactly the incident the trace is for;
 `pwasm-tpu trace-merge client.json daemon.json` joins the two.
"""

# distinct from every CLI exit code (1/3/5/75): "the service queue is
# full, back off and retry" — the shell-visible twin of HTTP 429
EXIT_QUEUE_FULL = 11


class ServiceError(Exception):
    """A protocol-level failure talking to the daemon."""


class ServiceClient:
    """One connection to a serve daemon — over a unix socket path or,
    since the fleet federation PR, a ``HOST:PORT`` TCP target (the
    grammar lives in ``pwasm_tpu/fleet/transport.py``; docs/FLEET.md).
    Context-manager; every command is one request/response frame pair
    on this connection.

    ``trace_id`` (minted per connection unless passed in) rides EVERY
    frame: the daemon stamps it onto the jobs this client submits —
    into the journal (surviving kill -9 replay), the event log, the
    flight record, and both sides' Chrome traces — so one grep (or one
    ``trace-merge``) reconstructs a job's whole cross-process life.

    ``client_token`` (the ``--client-token`` flag) also rides every
    frame: on TCP — where no kernel-attested ``SO_PEERCRED`` identity
    exists — the daemon buckets this connection's jobs under
    ``tok:<token>`` for DRR fair share, so identities stay
    attested-or-explicit on both transports."""

    def __init__(self, socket_path: str, timeout: float | None = None,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 trace_id: str | None = None,
                 client_token: str | None = None,
                 deadline_s: float | None = None,
                 tls=None):
        from pwasm_tpu.fleet.transport import connect
        from pwasm_tpu.obs.events import new_run_id
        self.socket_path = socket_path
        self.max_frame_bytes = max_frame_bytes
        self.trace_id = trace_id or new_run_id()
        self.client_token = client_token
        # TLS client config (transport.ClientTLS): applies to TCP
        # targets only — unix-socket connects ignore it, so ONE config
        # serves a mixed local+TCP fleet (ISSUE 19)
        self.tls = tls
        # ---- end-to-end deadline (ISSUE 18): --deadline-s mints ONE
        # monotonic deadline for this connection's jobs; every frame
        # carries the REMAINING budget as integer deadline_ms, so each
        # hop (router, daemon, supervisor) sees what is truly left
        # after the time already spent upstream.  None = no deadline:
        # frames are byte-identical to before the field existed.
        self.deadline_s = deadline_s
        self._deadline_mono = (time.monotonic() + deadline_s
                               if deadline_s else None)
        try:
            self._sock = connect(socket_path, timeout=timeout,
                                 tls=tls)
        except (OSError, ValueError) as e:
            raise ServiceError(
                f"cannot connect to service target {socket_path}: "
                f"{e}") from e
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")

    # ---- plumbing ------------------------------------------------------
    def _req(self, obj: dict) -> dict:
        """One command frame, trace_id stamped (the propagation rule:
        EVERY frame carries it, so even a bare status poll is
        correlatable in a packet capture) — and the client token when
        this connection has one (the TCP identity)."""
        obj.setdefault("trace_id", self.trace_id)
        if self._deadline_mono is not None:
            # remaining budget re-read per frame (never cached): a
            # frame sent after a long result wait must carry the truth
            obj.setdefault("deadline_ms",
                           max(0, int(self.deadline_remaining_s()
                                      * 1000)))
        return self.request(obj)

    def deadline_remaining_s(self) -> float:
        """Seconds left in this connection's ``--deadline-s`` budget
        (may be negative once spent); ``inf`` when no deadline is
        armed — the client side of the remaining-budget arithmetic."""
        if self._deadline_mono is None:
            return float("inf")
        return self._deadline_mono - time.monotonic()

    def request(self, obj: dict) -> dict:
        # the credential is a property of the CONNECTION, not of the
        # convenience verbs: raw frames (router→member polls, test
        # probes) must authenticate the same way _req-built ones do
        if self.client_token:
            obj.setdefault("client_token", self.client_token)
        try:
            protocol.write_frame(self._wfile, obj)
            resp = protocol.read_frame(self._rfile,
                                       self.max_frame_bytes)
        except (OSError, protocol.FrameError) as e:
            raise ServiceError(f"service connection failed: {e}") \
                from e
        if resp is None:
            raise ServiceError(
                "service closed the connection mid-request")
        return resp

    def close(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- commands ------------------------------------------------------
    def ping(self) -> dict:
        return self._req({"cmd": "ping"})

    def submit(self, argv: list[str], cwd: str | None = None,
               client: str | None = None,
               priority: str | None = None) -> dict:
        """Submit one job.  ``cwd`` (default: this process's cwd) is
        sent along so relative paths in the argv resolve against the
        CLIENT's directory, not the daemon's — what a cold run would
        do.  ``client`` overrides the fair-share identity the daemon
        would otherwise derive from the socket-peer uid; ``priority``
        names a ``--priority-lanes`` tier."""
        import os
        req: dict = {"cmd": "submit", "args": list(argv),
                     "cwd": cwd if cwd is not None else os.getcwd()}
        if client is not None:
            req["client"] = client
        if priority is not None:
            req["priority"] = priority
        return self._req(req)

    def status(self, job_id: str) -> dict:
        return self._req({"cmd": "status", "job_id": job_id})

    def result(self, job_id: str, wait: bool = True,
               timeout: float | None = None) -> dict:
        req: dict = {"cmd": "result", "job_id": job_id, "wait": wait}
        if timeout is not None:
            req["timeout"] = timeout
        return self._req(req)

    def cancel(self, job_id: str) -> dict:
        return self._req({"cmd": "cancel", "job_id": job_id})

    def inspect(self, job_id: str) -> dict:
        """The job's flight record (docs/OBSERVABILITY.md): phase
        walls + event ring, read from daemon RAM or the CRC-verified
        result spool."""
        return self._req({"cmd": "inspect", "job_id": job_id})

    # ---- streaming ingestion (docs/STREAMING.md) -----------------------
    def stream_open(self, argv: list[str], cwd: str | None = None,
                    client: str | None = None,
                    priority: str | None = None,
                    delta: bool = False) -> dict:
        """Admit a stream job: ``argv`` is a submit-shaped job argv
        WITHOUT a positional PAF (the records arrive over
        ``stream_data``).  ``delta=True`` opts the stream into cache
        delta classification (docs/STREAMING.md): the daemon holds
        early frames against its result cache's per-line digest
        columns, and a re-opened stream whose records extend a cached
        run is served that run's report and re-armed as a --resume —
        the file-side delta contract, over the socket."""
        import os
        req: dict = {"cmd": "stream", "args": list(argv),
                     "cwd": cwd if cwd is not None else os.getcwd()}
        if client is not None:
            req["client"] = client
        if priority is not None:
            req["priority"] = priority
        if delta:
            req["delta"] = True
        return self._req(req)

    def stream_data(self, job_id: str, data: str,
                    digests: list[str] | None = None) -> dict:
        """Feed one chunk of PAF text (any byte split — the daemon
        reassembles records across frames).  ``digests`` (optional,
        delta streams) carries the 16-hex per-line digests of the
        lines this chunk completes — advisory: the daemon recomputes
        its own column and refuses the frame on disagreement."""
        req: dict = {"cmd": "stream-data", "job_id": job_id,
                     "data": data}
        if digests is not None:
            req["digests"] = digests
        return self._req(req)

    def stream_end(self, job_id: str) -> dict:
        return self._req({"cmd": "stream-end", "job_id": job_id})

    def stream(self, argv: list[str], chunks,
               cwd: str | None = None, client: str | None = None,
               priority: str | None = None, max_retries: int = 8,
               sleep=time.sleep,
               keepalive_s: float | None = None,
               delta: bool = False) -> dict:
        """Open a stream job, feed every chunk from ``chunks``, and
        end the stream — with the backpressure dance built in: a
        ``queue_full`` mid-stream (the stream's buffer quota or fair
        share filled faster than the job drains it) waits
        :func:`retry_backoff_s` seconds (capped-exponential, seeded by
        the daemon's ``retry_after_s`` hint — the same schedule
        ``submit --retry`` uses) and resends the SAME frame; the
        attempt counter resets on every accepted frame.  Raises
        :class:`ServiceError` once one frame stays rejected past
        ``max_retries`` consecutive attempts, or on any non-429
        rejection.  Returns the open response, augmented with
        ``records`` (total the daemon assembled) and
        ``backpressure_waits`` (how often the dance was danced) —
        call :meth:`result` with the returned ``job_id`` to wait for
        the report.

        ``keepalive_s``: while this thread is blocked pulling the
        NEXT chunk from a slow producer (a minimap2 index build can
        go silent for minutes), a helper thread on its OWN
        connection sends an empty ``stream-data`` frame every that
        many seconds — empty frames carry no records but count as
        stream activity, so the daemon's ``--stream-idle-s`` reaper
        never mistakes a slow producer for a vanished client."""
        resp = self.stream_open(argv, cwd=cwd, client=client,
                                priority=priority, delta=delta)
        if not resp.get("ok"):
            return resp
        job_id = resp["job_id"]
        masm = None
        if delta:
            # mirror the daemon's line assembly so each frame carries
            # the digests of exactly the lines it completes (the
            # daemon cross-checks; state advances once per chunk, so
            # a backpressure resend repeats identical digests)
            from pwasm_tpu.service.cache import line_digest
            from pwasm_tpu.stream.pafstream import LineAssembler
            masm = LineAssembler()
        stop = beat = None
        if keepalive_s:
            import threading
            stop = threading.Event()

            def _beat():
                # a SEPARATE connection: two threads interleaving
                # frames on one socket would corrupt the one-request/
                # one-response pairing
                try:
                    with ServiceClient(self.socket_path,
                                       trace_id=self.trace_id,
                                       client_token=self.client_token,
                                       tls=self.tls) \
                            as kc:
                        while not stop.wait(keepalive_s):
                            if not kc.stream_data(job_id,
                                                  "").get("ok"):
                                return
                except ServiceError:
                    pass      # best-effort: the feed itself decides

            beat = threading.Thread(target=_beat, daemon=True)
            beat.start()
        waits = 0
        try:
            for chunk in chunks:
                digs = [line_digest(ln) for ln in masm.push(chunk)] \
                    if masm is not None else None
                attempt = 0
                while True:
                    r = self.stream_data(job_id, chunk, digests=digs)
                    if r.get("ok"):
                        break
                    if r.get("error") != protocol.ERR_QUEUE_FULL:
                        raise ServiceError(
                            f"stream-data rejected: {r}")
                    if attempt >= max_retries:
                        raise ServiceError(
                            f"stream backpressure budget spent "
                            f"({max_retries} consecutive retries): "
                            f"{r}")
                    sleep(retry_backoff_s(attempt,
                                          r.get("retry_after_s")))
                    waits += 1
                    attempt += 1
        finally:
            if stop is not None:
                stop.set()
                beat.join(5)
        attempt = 0
        while True:
            # a delta-held stream resolves AT stream-end (late queue
            # entry), so even the end frame can answer queue_full —
            # same backoff-and-resend dance as a data frame
            end = self.stream_end(job_id)
            if end.get("ok"):
                break
            if end.get("error") != protocol.ERR_QUEUE_FULL \
                    or attempt >= max_retries:
                raise ServiceError(f"stream-end rejected: {end}")
            sleep(retry_backoff_s(attempt, end.get("retry_after_s")))
            waits += 1
            attempt += 1
        resp["records"] = end.get("records")
        resp["backpressure_waits"] = waits
        return resp

    def stats(self) -> dict:
        return self._req({"cmd": "stats"})

    def cache_probe(self, key: str) -> dict:
        """Would this daemon's result cache answer ``key``?  A cheap
        manifest check (``{"hit":bool,"enabled":bool}``) — the fleet
        router's cache-affinity placement probe (docs/SERVICE.md)."""
        return self._req({"cmd": "cache-probe", "key": key})

    def metrics(self, exemplars: bool = False) -> dict:
        """Prometheus text exposition; ``exemplars=True`` opts into
        the OpenMetrics exemplar suffix on histogram buckets (strict
        0.0.4 parsers reject it, so the default stays pure)."""
        req: dict = {"cmd": "metrics"}
        if exemplars:
            req["exemplars"] = True
        return self._req(req)

    def health(self) -> dict:
        """The machine-readable health verdict (docs/OBSERVABILITY.md):
        ok/degraded/failing + firing SLO rules (+ member verdicts
        when the target is a fleet router)."""
        return self._req({"cmd": "health"})

    def logs(self, trace_id: str | None = None,
             job_id: str | None = None, event: str | None = None,
             limit: int = 1000) -> dict:
        """Query the daemon's --log-json event log (rotation-aware).
        The filter rides as ``filter_trace_id`` because every frame
        already carries this CONNECTION's trace_id."""
        req: dict = {"cmd": "logs", "limit": limit}
        if trace_id is not None:
            req["filter_trace_id"] = trace_id
        if job_id is not None:
            req["job_id"] = job_id
        if event is not None:
            req["event"] = event
        return self._req(req)

    def drain(self) -> dict:
        return self._req({"cmd": "drain"})


def retry_backoff_s(attempt: int, hint_s: float | None,
                    base_s: float = 0.5, cap_s: float = 30.0) -> float:
    """The ``submit --retry`` backoff schedule: wait before retry
    number ``attempt`` (0-based) after a ``queue_full``.  The daemon's
    ``retry_after_s`` hint (~one recent job wall) seeds the first
    wait; each consecutive rejection doubles it, capped at ``cap_s``
    so a long outage polls steadily instead of going silent for
    minutes.  Pure and deterministic — the unit-tested contract; the
    caller adds no jitter because the daemon's hint already differs
    per client (it tracks that daemon's own job walls)."""
    if not isinstance(hint_s, (int, float)) or not hint_s > 0:
        hint_s = base_s
    return min(float(cap_s), float(hint_s) * (2.0 ** max(0, attempt)))


def wait_for_socket(path: str, budget_s: float = 30.0) -> bool:
    """Block (bounded) until a daemon answers on ``path`` — the
    "did the serve process come up" primitive for the bench and the
    subprocess tests."""
    deadline = time.monotonic() + max(0.0, budget_s)
    while True:
        try:
            with ServiceClient(path, timeout=1.0) as c:
                if c.ping().get("ok"):
                    return True
        except ServiceError:
            pass
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.05)


def _parse_client_argv(argv: list[str],
                       cmd: str | None = None) -> tuple[dict,
                                                        list[str]]:
    """Split client flags from the job argv: client flags are read
    until the first ``--`` or the first token that is not a recognized
    client flag (so both ``submit --socket=S -- in.paf ...`` and
    ``submit --socket=S in.paf ...`` work).  The verb-specific flags
    (``--exit-code`` on health, ``--job``/``--event``/``--limit`` on
    logs, ``--exemplars`` on metrics) are recognized ONLY for their
    verb — on any other verb they fall through to the job argv and
    fail its validation loudly instead of being silently swallowed."""
    opts: dict = {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--":
            i += 1
            break
        if a.startswith("--socket="):
            opts["socket"] = a.split("=", 1)[1]
        elif a == "--no-wait":
            opts["no_wait"] = True
        elif a == "--drain":
            opts["drain"] = True
        elif a.startswith("--timeout="):
            opts["timeout"] = a.split("=", 1)[1]
        elif a == "--retry":
            opts["retry"] = "5"
        elif a.startswith("--retry="):
            opts["retry"] = a.split("=", 1)[1]
        elif a.startswith("--client="):
            opts["client"] = a.split("=", 1)[1]
        elif a.startswith("--client-token="):
            opts["client_token"] = a.split("=", 1)[1]
        elif a.startswith("--priority="):
            opts["priority"] = a.split("=", 1)[1]
        elif a.startswith("--deadline-s=") and cmd in ("submit",
                                                       "stream"):
            opts["deadline_s"] = a.split("=", 1)[1]
        elif a.startswith("--trace-id="):
            opts["trace_id"] = a.split("=", 1)[1]
        elif a.startswith("--trace-json="):
            opts["trace_json"] = a.split("=", 1)[1]
        elif a.startswith("--tls-ca="):
            opts["tls_ca"] = a.split("=", 1)[1]
        elif a.startswith("--tls-cert="):
            opts["tls_cert"] = a.split("=", 1)[1]
        elif a.startswith("--tls-key="):
            opts["tls_key"] = a.split("=", 1)[1]
        elif a == "--exit-code" and cmd == "health":
            opts["exit_code"] = True
        elif a == "--exemplars" and cmd == "metrics":
            opts["exemplars"] = True
        elif a.startswith("--job=") and cmd == "logs":
            opts["job"] = a.split("=", 1)[1]
        elif a.startswith("--event=") and cmd == "logs":
            opts["event"] = a.split("=", 1)[1]
        elif a.startswith("--limit=") and cmd == "logs":
            opts["limit"] = a.split("=", 1)[1]
        else:
            break
        i += 1
    return opts, argv[i:]


def _job_verdict(resp: dict, job_id: str, stdout, stderr,
                 client=None) -> int:
    """Render a ``result`` response the way ``submit`` always has (one
    JSON verdict line, the stderr tail of a non-done job) and return
    the shell exit code — shared by the ``submit`` and ``stream``
    verbs so the two cannot drift.  When the connection carries a
    ``--deadline-s`` budget, the verdict shows the remaining-budget
    arithmetic (budget granted, seconds left at verdict time) so an
    operator can see at a glance whether a resume is worth a fresh
    budget; without a deadline the verdict is byte-identical to
    before the field existed."""
    if not resp.get("ok"):
        stderr.write(f"Error: result failed: {resp}\n")
        return EXIT_FATAL
    if resp.get("pending"):
        stderr.write(f"Error: job {job_id} still "
                     f"{resp['job']['state']} after the "
                     "--timeout\n")
        return EXIT_FATAL
    job = resp["job"]
    verdict = {"job_id": job_id, "state": job["state"],
               "rc": resp.get("rc"), "detail": job.get("detail"),
               "trace_id": job.get("trace_id")}
    if client is not None and client.deadline_s:
        verdict["deadline"] = {
            "budget_s": round(float(client.deadline_s), 3),
            "remaining_s": round(client.deadline_remaining_s(), 3)}
    json.dump(verdict, stdout)
    stdout.write("\n")
    tail = resp.get("stderr_tail") or ""
    if tail and job["state"] != "done":
        stderr.write(tail)
    rc = resp.get("rc")
    return rc if isinstance(rc, int) else EXIT_FATAL


def _logs_main(opts: dict, positional: list[str],
               sock: str | None, stdout, stderr, tls=None) -> int:
    """The ``pwasm-tpu logs`` verb: socket mode asks the daemon to
    filter its own ``--log-json``; FILE mode runs the SAME filter
    (``obs/logquery.py``) over a log on disk — the two cannot
    disagree.  Output is NDJSON, oldest-first, newest --limit kept."""
    # flags may follow the FILE positional (`logs ev.ndjson
    # --event=x` reads as naturally as the flag-first order the
    # generic client parser stops at) — sweep the remainder here
    rest: list[str] = []
    for a in positional:
        if a.startswith("--trace-id="):
            opts["trace_id"] = a.split("=", 1)[1]
        elif a.startswith("--job="):
            opts["job"] = a.split("=", 1)[1]
        elif a.startswith("--event="):
            opts["event"] = a.split("=", 1)[1]
        elif a.startswith("--limit="):
            opts["limit"] = a.split("=", 1)[1]
        else:
            rest.append(a)
    positional = rest
    limit = 1000
    if "limit" in opts:
        val = opts["limit"]
        if not (val.isascii() and val.isdigit()
                and 1 <= int(val) <= 10000):
            stderr.write(f"{_CLIENT_USAGE}\nInvalid --limit value: "
                         f"{val}\n")
            return EXIT_USAGE
        limit = int(val)
    trace_id = opts.get("trace_id")
    job_id = opts.get("job")
    event = opts.get("event")
    if sock:
        if positional:
            stderr.write(f"{_CLIENT_USAGE}\nError: logs takes "
                         "--socket OR a log FILE, not both\n")
            return EXIT_USAGE
        try:
            with ServiceClient(sock, tls=tls) as c:
                resp = c.logs(trace_id=trace_id, job_id=job_id,
                              event=event, limit=limit)
        except ServiceError as e:
            stderr.write(f"Error: {e}\n")
            return EXIT_FATAL
        if not resp.get("ok"):
            stderr.write(f"Error: logs failed "
                         f"({resp.get('error')}): "
                         f"{resp.get('detail', '')}\n")
            return EXIT_FATAL
        lines = resp.get("lines") or []
    else:
        if len(positional) != 1:
            stderr.write(f"{_CLIENT_USAGE}\nError: logs needs "
                         "--socket=TARGET or exactly one log FILE\n")
            return EXIT_USAGE
        import os
        path = positional[0]
        if not os.path.exists(path) \
                and not os.path.exists(path + ".1"):
            stderr.write(f"Error: no event log at {path}\n")
            return EXIT_FATAL
        from pwasm_tpu.obs.logquery import query_log
        lines = query_log(path, trace_id=trace_id, job_id=job_id,
                          event=event, limit=limit)
    for rec in lines:
        json.dump(rec, stdout, separators=(",", ":"))
        stdout.write("\n")
    return 0


def client_main(cmd: str, argv: list[str], stdout=None,
                stderr=None) -> int:
    """The ``pwasm-tpu submit`` / ``pwasm-tpu stream`` /
    ``pwasm-tpu svc-stats`` entry point."""
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    opts, job_argv = _parse_client_argv(argv, cmd)
    sock = opts.get("socket")
    # TLS client config (ISSUE 19): --tls-ca verifies the server,
    # --tls-cert/--tls-key present a client certificate (mTLS).
    # Applies to TCP targets; a unix-socket connect ignores it.
    tls = None
    if "tls_ca" in opts:
        from pwasm_tpu.fleet.transport import ClientTLS
        try:
            tls = ClientTLS(opts["tls_ca"],
                            certfile=opts.get("tls_cert"),
                            keyfile=opts.get("tls_key"))
        except ValueError as e:
            stderr.write(f"Error: {e}\n")
            return EXIT_USAGE
    elif "tls_cert" in opts or "tls_key" in opts:
        stderr.write(f"{_CLIENT_USAGE}\nError: --tls-cert/--tls-key "
                     "need --tls-ca=PEM (the CA that vouches for "
                     "the server)\n")
        return EXIT_USAGE
    if cmd == "logs":
        # the one socket-optional verb: `logs FILE` queries a log on
        # disk directly (same filter engine the daemon runs)
        return _logs_main(opts, job_argv, sock, stdout, stderr,
                          tls=tls)
    if not sock:
        stderr.write(f"{_CLIENT_USAGE}\nError: --socket=PATH is "
                     "required\n")
        return EXIT_USAGE
    timeout: float | None = None
    if "timeout" in opts:
        try:
            timeout = float(opts["timeout"])
            if timeout <= 0:
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_CLIENT_USAGE}\nInvalid --timeout value: "
                         f"{opts['timeout']}\n")
            return EXIT_USAGE
    deadline_s: float | None = None
    if "deadline_s" in opts:
        import math
        try:
            deadline_s = float(opts["deadline_s"])
            if deadline_s <= 0 or not math.isfinite(deadline_s):
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_CLIENT_USAGE}\nInvalid --deadline-s "
                         f"value: {opts['deadline_s']} (need a "
                         "positive finite number of seconds)\n")
            return EXIT_USAGE
    # --trace-json: record THIS process's side of the job (the RPC
    # spans) as a wall-anchored Chrome trace — the `trace-merge`
    # counterpart of the daemon's serve --trace-json.  Built up here
    # so both the submit and stream verbs share it.
    tracer = None
    if "trace_json" in opts:
        from pwasm_tpu.obs import TraceRecorder
        tracer = TraceRecorder()

    def _span(name: str, t0, c) -> None:
        if tracer is not None:
            tracer.complete(name, t0, trace_id=c.trace_id)

    def _write_trace() -> None:
        if tracer is not None:
            try:
                tracer.write(opts["trace_json"])
                stderr.write(f"pwasm: client trace written to "
                             f"{opts['trace_json']}\n")
            except OSError as e:
                stderr.write(f"Warning: cannot write "
                             f"--trace-json {opts['trace_json']}:"
                             f" {e}\n")

    try:
        if cmd == "metrics":
            with ServiceClient(
                    sock, trace_id=opts.get("trace_id"),
                    client_token=opts.get("client_token"),
                    tls=tls) as c:
                resp = c.metrics(
                    exemplars=bool(opts.get("exemplars")))
            if not resp.get("ok"):
                stderr.write(f"Error: metrics failed: {resp}\n")
                return EXIT_FATAL
            stdout.write(resp.get("metrics", ""))
            return 0
        if cmd == "health":
            with ServiceClient(
                    sock, trace_id=opts.get("trace_id"),
                    client_token=opts.get("client_token"),
                    tls=tls) as c:
                resp = c.health()
            if not resp.get("ok"):
                stderr.write(f"Error: health failed "
                             f"({resp.get('error')}): "
                             f"{resp.get('detail', '')}\n")
                return EXIT_FATAL
            health = resp.get("health") or {}
            json.dump(health, stdout, indent=2)
            stdout.write("\n")
            if opts.get("exit_code"):
                # the orchestrator-probe form: 0 ok / 1 degraded /
                # 2 failing (unknown ranks degraded — a probe must
                # never read a parse problem as health)
                from pwasm_tpu.obs.slo import verdict_exit_code
                return verdict_exit_code(health.get("verdict"))
            return 0
        if cmd == "inspect":
            if len(job_argv) != 1:
                stderr.write(f"{_CLIENT_USAGE}\nError: inspect needs "
                             "exactly one JOB_ID\n")
                return EXIT_USAGE
            with ServiceClient(
                    sock, trace_id=opts.get("trace_id"),
                    client_token=opts.get("client_token"),
                    tls=tls) as c:
                resp = c.inspect(job_argv[0])
            if not resp.get("ok"):
                stderr.write(f"Error: inspect failed "
                             f"({resp.get('error')}): "
                             f"{resp.get('detail', '')}\n")
                return EXIT_FATAL
            json.dump({"job": resp.get("job"),
                       "trace_id": resp.get("trace_id"),
                       "flight": resp.get("flight"),
                       **({"spool_error": resp["spool_error"]}
                          if "spool_error" in resp else {})},
                      stdout, indent=2)
            stdout.write("\n")
            return 0
        if cmd == "svc-stats":
            with ServiceClient(
                    sock, trace_id=opts.get("trace_id"),
                    client_token=opts.get("client_token"),
                    tls=tls) as c:
                if opts.get("drain"):
                    resp = c.drain()
                    if not resp.get("ok"):
                        stderr.write(f"Error: drain rejected: "
                                     f"{resp}\n")
                        return EXIT_FATAL
                resp = c.stats()
            if not resp.get("ok"):
                stderr.write(f"Error: stats failed: {resp}\n")
                return EXIT_FATAL
            json.dump(resp["stats"], stdout)
            stdout.write("\n")
            return 0
        if cmd == "stream":
            # the minimap2-pipe verb: stdin is the record source, fed
            # record-at-a-time with automatic backpressure handling
            if not job_argv:
                stderr.write(f"{_CLIENT_USAGE}\nError: stream needs "
                             "the job's CLI arguments\n")
                return EXIT_USAGE
            # available-bytes chunking (read1): frames carry whatever
            # the pipe has — low latency on a trickling producer, yet
            # one frame per ~64 KiB on a firehose instead of one RPC
            # per record (the daemon reassembles records across
            # frames either way).  Streams without a .buffer (tests
            # hand a StringIO) fall back to per-line frames.
            buf = getattr(sys.stdin, "buffer", None)
            if buf is not None:
                src = (b.decode("utf-8", "replace") for b in
                       iter(lambda: buf.read1(1 << 16), b""))
            else:
                src = iter(sys.stdin.readline, "")
            with ServiceClient(
                    sock, trace_id=opts.get("trace_id"),
                    client_token=opts.get("client_token"),
                    deadline_s=deadline_s, tls=tls) as c:
                t0 = tracer.now() if tracer is not None else 0.0
                resp = c.stream(job_argv, src,
                                client=opts.get("client"),
                                priority=opts.get("priority"),
                                keepalive_s=30.0)
                _span("stream_feed", t0, c)
                if not resp.get("ok"):
                    code = resp.get("error")
                    stderr.write(f"Error: stream rejected ({code}): "
                                 f"{resp.get('detail', '')}\n")
                    _write_trace()
                    return EXIT_QUEUE_FULL \
                        if code == protocol.ERR_QUEUE_FULL \
                        else EXIT_FATAL
                job_id = resp["job_id"]
                t0 = tracer.now() if tracer is not None else 0.0
                resp = c.result(job_id, wait=True, timeout=timeout)
                _span("result_wait", t0, c)
            _write_trace()
            return _job_verdict(resp, job_id, stdout, stderr,
                                client=c)
        # submit
        if not job_argv:
            stderr.write(f"{_CLIENT_USAGE}\nError: submit needs the "
                         "job's CLI arguments\n")
            return EXIT_USAGE
        retries = 0
        if "retry" in opts:
            val = opts["retry"]
            if not (val.isascii() and val.isdigit() and int(val) >= 1):
                stderr.write(f"{_CLIENT_USAGE}\nInvalid --retry "
                             f"value: {val}\n")
                return EXIT_USAGE
            retries = int(val)
        with ServiceClient(
                sock, trace_id=opts.get("trace_id"),
                client_token=opts.get("client_token"),
                deadline_s=deadline_s, tls=tls) as c:
            for attempt in range(retries + 1):
                t0 = tracer.now() if tracer is not None else 0.0
                resp = c.submit(job_argv, client=opts.get("client"),
                                priority=opts.get("priority"))
                _span("submit_rpc", t0, c)
                if resp.get("ok") \
                        or resp.get("error") != protocol.ERR_QUEUE_FULL \
                        or attempt >= retries:
                    break
                # the 429 dance: honor the daemon's hint, doubling per
                # consecutive rejection (capped — see retry_backoff_s)
                wait = retry_backoff_s(attempt,
                                       resp.get("retry_after_s"))
                stderr.write(f"pwasm: queue full "
                             f"({resp.get('detail', '')}); retry "
                             f"{attempt + 1}/{retries} in "
                             f"{wait:.2f}s\n")
                time.sleep(wait)
            if not resp.get("ok"):
                code = resp.get("error")
                stderr.write(f"Error: submission rejected "
                             f"({code}): {resp.get('detail', '')}\n")
                _write_trace()
                if code == protocol.ERR_QUEUE_FULL:
                    hint = resp.get("retry_after_s")
                    if hint is not None:
                        stderr.write(f"(retry after ~{hint}s)\n")
                    return EXIT_QUEUE_FULL
                return EXIT_FATAL
            job_id = resp["job_id"]
            if opts.get("no_wait"):
                json.dump({"job_id": job_id, "state": "queued",
                           "trace_id": resp.get("trace_id")},
                          stdout)
                stdout.write("\n")
                _write_trace()
                return 0
            t0 = tracer.now() if tracer is not None else 0.0
            resp = c.result(job_id, wait=True, timeout=timeout)
            _span("result_wait", t0, c)
        _write_trace()
        return _job_verdict(resp, job_id, stdout, stderr, client=c)
    except ServiceError as e:
        stderr.write(f"Error: {e}\n")
        # the client-side trace is most valuable exactly when the
        # daemon died mid-job: flush whatever spans landed
        _write_trace()
        return EXIT_FATAL
