"""Client side of the warm-pool service (``pwasm-tpu submit`` /
``pwasm-tpu svc-stats``) and the :class:`ServiceClient` library the
bench, QA drills and tests drive.

A client is one unix-socket connection speaking the newline-delimited
JSON protocol (``service.protocol``).  ``submit`` is the cold-CLI
drop-in: the job argv after ``--`` (or after the client flags) is
exactly what a cold ``python -m pwasm_tpu.cli`` invocation would take,
and the client's exit code is the job's exit code — so a fleet wrapper
can switch between cold runs and warm submissions by prefixing
``submit --socket=PATH --`` and nothing else changes.
"""

from __future__ import annotations

import json
import socket
import sys
import time

from pwasm_tpu.core.errors import EXIT_FATAL, EXIT_USAGE
from pwasm_tpu.service import protocol

_CLIENT_USAGE = """Usage:
 pwasm-tpu submit --socket=PATH [--no-wait] [--timeout=S]
                  [--retry[=N]] [--client=NAME] [--priority=LANE]
                  [--] <cli args...>
     submit one report job (the argv a cold CLI run would take; -o is
     required — the socket carries control, not report bytes).  By
     default waits for the job and exits with the JOB's exit code
     (0 done, 75 preempted/cancelled-resumable, else failed); with
     --no-wait prints the job id and exits 0.  A full queue
     (queue_full) exits 11 so wrappers can back off and retry — or
     pass --retry[=N] (default 5 attempts) and the client backs off
     ITSELF: capped-exponential waits seeded by the daemon's
     retry_after_s hint, exiting 11 only once the budget is spent.
     --client=NAME overrides the fair-share identity (default: the
     socket-peer uid); --priority=LANE targets a --priority-lanes
     tier on the daemon.

 pwasm-tpu svc-stats --socket=PATH [--drain]
     print the service-level stats JSON (versioned schema); with
     --drain, ask the daemon to drain gracefully first (running jobs
     finish at batch boundaries, queued jobs report resumable, daemon
     exits 75).

 pwasm-tpu metrics --socket=PATH
     print the daemon's metrics as Prometheus text exposition (queue
     depth, in-flight jobs, breaker state, job wall/queue-wait
     histograms, cumulative per-run counters) — the socket twin of
     `serve --metrics-textfile=PATH` (docs/OBSERVABILITY.md).
"""

# distinct from every CLI exit code (1/3/5/75): "the service queue is
# full, back off and retry" — the shell-visible twin of HTTP 429
EXIT_QUEUE_FULL = 11


class ServiceError(Exception):
    """A protocol-level failure talking to the daemon."""


class ServiceClient:
    """One connection to a serve daemon.  Context-manager; every
    command is one request/response frame pair on this connection."""

    def __init__(self, socket_path: str, timeout: float | None = None,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES):
        self.socket_path = socket_path
        self.max_frame_bytes = max_frame_bytes
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            self._sock.connect(socket_path)
        except OSError as e:
            self._sock.close()
            raise ServiceError(
                f"cannot connect to service socket {socket_path}: "
                f"{e}") from e
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")

    # ---- plumbing ------------------------------------------------------
    def request(self, obj: dict) -> dict:
        try:
            protocol.write_frame(self._wfile, obj)
            resp = protocol.read_frame(self._rfile,
                                       self.max_frame_bytes)
        except (OSError, protocol.FrameError) as e:
            raise ServiceError(f"service connection failed: {e}") \
                from e
        if resp is None:
            raise ServiceError(
                "service closed the connection mid-request")
        return resp

    def close(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- commands ------------------------------------------------------
    def ping(self) -> dict:
        return self.request({"cmd": "ping"})

    def submit(self, argv: list[str], cwd: str | None = None,
               client: str | None = None,
               priority: str | None = None) -> dict:
        """Submit one job.  ``cwd`` (default: this process's cwd) is
        sent along so relative paths in the argv resolve against the
        CLIENT's directory, not the daemon's — what a cold run would
        do.  ``client`` overrides the fair-share identity the daemon
        would otherwise derive from the socket-peer uid; ``priority``
        names a ``--priority-lanes`` tier."""
        import os
        req: dict = {"cmd": "submit", "args": list(argv),
                     "cwd": cwd if cwd is not None else os.getcwd()}
        if client is not None:
            req["client"] = client
        if priority is not None:
            req["priority"] = priority
        return self.request(req)

    def status(self, job_id: str) -> dict:
        return self.request({"cmd": "status", "job_id": job_id})

    def result(self, job_id: str, wait: bool = True,
               timeout: float | None = None) -> dict:
        req: dict = {"cmd": "result", "job_id": job_id, "wait": wait}
        if timeout is not None:
            req["timeout"] = timeout
        return self.request(req)

    def cancel(self, job_id: str) -> dict:
        return self.request({"cmd": "cancel", "job_id": job_id})

    def stats(self) -> dict:
        return self.request({"cmd": "stats"})

    def metrics(self) -> dict:
        return self.request({"cmd": "metrics"})

    def drain(self) -> dict:
        return self.request({"cmd": "drain"})


def retry_backoff_s(attempt: int, hint_s: float | None,
                    base_s: float = 0.5, cap_s: float = 30.0) -> float:
    """The ``submit --retry`` backoff schedule: wait before retry
    number ``attempt`` (0-based) after a ``queue_full``.  The daemon's
    ``retry_after_s`` hint (~one recent job wall) seeds the first
    wait; each consecutive rejection doubles it, capped at ``cap_s``
    so a long outage polls steadily instead of going silent for
    minutes.  Pure and deterministic — the unit-tested contract; the
    caller adds no jitter because the daemon's hint already differs
    per client (it tracks that daemon's own job walls)."""
    if not isinstance(hint_s, (int, float)) or not hint_s > 0:
        hint_s = base_s
    return min(float(cap_s), float(hint_s) * (2.0 ** max(0, attempt)))


def wait_for_socket(path: str, budget_s: float = 30.0) -> bool:
    """Block (bounded) until a daemon answers on ``path`` — the
    "did the serve process come up" primitive for the bench and the
    subprocess tests."""
    deadline = time.monotonic() + max(0.0, budget_s)
    while True:
        try:
            with ServiceClient(path, timeout=1.0) as c:
                if c.ping().get("ok"):
                    return True
        except ServiceError:
            pass
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.05)


def _parse_client_argv(argv: list[str]) -> tuple[dict, list[str]]:
    """Split client flags from the job argv: client flags are read
    until the first ``--`` or the first token that is not a recognized
    client flag (so both ``submit --socket=S -- in.paf ...`` and
    ``submit --socket=S in.paf ...`` work)."""
    opts: dict = {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--":
            i += 1
            break
        if a.startswith("--socket="):
            opts["socket"] = a.split("=", 1)[1]
        elif a == "--no-wait":
            opts["no_wait"] = True
        elif a == "--drain":
            opts["drain"] = True
        elif a.startswith("--timeout="):
            opts["timeout"] = a.split("=", 1)[1]
        elif a == "--retry":
            opts["retry"] = "5"
        elif a.startswith("--retry="):
            opts["retry"] = a.split("=", 1)[1]
        elif a.startswith("--client="):
            opts["client"] = a.split("=", 1)[1]
        elif a.startswith("--priority="):
            opts["priority"] = a.split("=", 1)[1]
        else:
            break
        i += 1
    return opts, argv[i:]


def client_main(cmd: str, argv: list[str], stdout=None,
                stderr=None) -> int:
    """The ``pwasm-tpu submit`` / ``pwasm-tpu svc-stats`` entry
    point."""
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    opts, job_argv = _parse_client_argv(argv)
    sock = opts.get("socket")
    if not sock:
        stderr.write(f"{_CLIENT_USAGE}\nError: --socket=PATH is "
                     "required\n")
        return EXIT_USAGE
    timeout: float | None = None
    if "timeout" in opts:
        try:
            timeout = float(opts["timeout"])
            if timeout <= 0:
                raise ValueError
        except (TypeError, ValueError):
            stderr.write(f"{_CLIENT_USAGE}\nInvalid --timeout value: "
                         f"{opts['timeout']}\n")
            return EXIT_USAGE
    try:
        if cmd == "metrics":
            with ServiceClient(sock) as c:
                resp = c.metrics()
            if not resp.get("ok"):
                stderr.write(f"Error: metrics failed: {resp}\n")
                return EXIT_FATAL
            stdout.write(resp.get("metrics", ""))
            return 0
        if cmd == "svc-stats":
            with ServiceClient(sock) as c:
                if opts.get("drain"):
                    resp = c.drain()
                    if not resp.get("ok"):
                        stderr.write(f"Error: drain rejected: "
                                     f"{resp}\n")
                        return EXIT_FATAL
                resp = c.stats()
            if not resp.get("ok"):
                stderr.write(f"Error: stats failed: {resp}\n")
                return EXIT_FATAL
            json.dump(resp["stats"], stdout)
            stdout.write("\n")
            return 0
        # submit
        if not job_argv:
            stderr.write(f"{_CLIENT_USAGE}\nError: submit needs the "
                         "job's CLI arguments\n")
            return EXIT_USAGE
        retries = 0
        if "retry" in opts:
            val = opts["retry"]
            if not (val.isascii() and val.isdigit() and int(val) >= 1):
                stderr.write(f"{_CLIENT_USAGE}\nInvalid --retry "
                             f"value: {val}\n")
                return EXIT_USAGE
            retries = int(val)
        with ServiceClient(sock) as c:
            for attempt in range(retries + 1):
                resp = c.submit(job_argv, client=opts.get("client"),
                                priority=opts.get("priority"))
                if resp.get("ok") \
                        or resp.get("error") != protocol.ERR_QUEUE_FULL \
                        or attempt >= retries:
                    break
                # the 429 dance: honor the daemon's hint, doubling per
                # consecutive rejection (capped — see retry_backoff_s)
                wait = retry_backoff_s(attempt,
                                       resp.get("retry_after_s"))
                stderr.write(f"pwasm: queue full "
                             f"({resp.get('detail', '')}); retry "
                             f"{attempt + 1}/{retries} in "
                             f"{wait:.2f}s\n")
                time.sleep(wait)
            if not resp.get("ok"):
                code = resp.get("error")
                stderr.write(f"Error: submission rejected "
                             f"({code}): {resp.get('detail', '')}\n")
                if code == protocol.ERR_QUEUE_FULL:
                    hint = resp.get("retry_after_s")
                    if hint is not None:
                        stderr.write(f"(retry after ~{hint}s)\n")
                    return EXIT_QUEUE_FULL
                return EXIT_FATAL
            job_id = resp["job_id"]
            if opts.get("no_wait"):
                json.dump({"job_id": job_id, "state": "queued"},
                          stdout)
                stdout.write("\n")
                return 0
            resp = c.result(job_id, wait=True, timeout=timeout)
        if not resp.get("ok"):
            stderr.write(f"Error: result failed: {resp}\n")
            return EXIT_FATAL
        if resp.get("pending"):
            stderr.write(f"Error: job {job_id} still "
                         f"{resp['job']['state']} after the "
                         "--timeout\n")
            return EXIT_FATAL
        job = resp["job"]
        json.dump({"job_id": job_id, "state": job["state"],
                   "rc": resp.get("rc"), "detail": job.get("detail")},
                  stdout)
        stdout.write("\n")
        tail = resp.get("stderr_tail") or ""
        if tail and job["state"] != "done":
            stderr.write(tail)
        rc = resp.get("rc")
        return rc if isinstance(rc, int) else EXIT_FATAL
    except ServiceError as e:
        stderr.write(f"Error: {e}\n")
        return EXIT_FATAL
