"""Content-addressed result cache: repeat traffic in microseconds.

At production scale the traffic this service exists for is heavily
repetitive — the same bacterial CDS re-scored against overlapping
assembly sets every time basecalling re-runs (ROADMAP item 2).  Until
now an identical job paid the full queue→lease→device→format pipeline
even though the spool already held its exact output bytes.  This
module closes that: a finished job's output files are stored under a
**content-addressed key** and an identical later job — same inputs by
DIGEST, same result-affecting flags by CANONICAL FORM — is served the
stored bytes with zero device, lease, or queue involvement.

The key
-------

``sha256`` over a canonical JSON document of:

- the **canonicalized ref-FASTA digest** (:func:`fasta_digest`:
  per-record ``>name`` + uppercased whitespace-stripped sequence, so
  cosmetic line wrapping or case cannot split the cache);
- the **input digest** (:func:`digest_file` over the PAF bytes, or
  :func:`fasta_digest` for a ``--many2many`` target FASTA — computed
  in ONE ``mmap``/block pass, and on the ingest side the same pass
  that feeds the run, see ``stream.pafstream.BlockLineReader``);
- the **result-affecting flag set** in canonical (sorted) form — see
  :data:`KEYED_BOOL` / :data:`KEYED_VALUE` / :data:`KEYED_FILE`: a
  cosmetic argv reorder still hits, while anything that changes
  output BYTES (mode flags, ``-c``, ``--band``, motif content) keys a
  distinct entry.  Flags that provably do not change bytes
  (``--device``/``--batch``/resilience knobs — the repo's byte-parity
  contracts) and per-invocation plumbing (output PATHS, obs sinks,
  ``--socket``-side fields) are EXCLUDED, so the same logical job
  hits regardless of where its report lands;
- the requested **output kinds** (``o``/``s``/``w``/``ace``/``info``/
  ``cons`` — kinds are keyed, paths are not), so an entry always
  holds exactly the output set its hits need.

A job carrying a flag outside the table — or one whose semantics are
inherently uncacheable (``--resume``, ``--follow``, a socket stream,
``--inject-faults``) — **bypasses** the cache entirely
(:func:`classify` returns ``None``): unknown means "cannot vouch for
byte identity", and the safe direction is always a real run.

Storage
-------

The PR 9 spool discipline: per entry one CRC'd manifest
(``<key>.json``, written via the audited ``fsio`` fsync-then-replace
— the COMMIT POINT) plus one blob file per output kind
(``<key>.<kind>``), each blob's size+CRC32 recorded in the manifest.
A ``kill -9`` mid-insert leaves blobs without a manifest — orphans a
startup :meth:`CacheStore.sweep` removes; a manifest whose blob rotted
(CRC mismatch) is a MISS and the entry is dropped, never a corrupt
serve.

Delta entries (incremental compute)
-----------------------------------

An appended assembly voids the exact key but not the work: report-only
entries additionally record a **delta index** — one 16-hex digest per
input line (``<key>.dx`` sidecar, CRC'd through the manifest ``delta``
block) plus a **family key** (:func:`family_key`: the exact key minus
the input digest).  A near-miss in the same family whose cached input
is a per-line PREFIX of the new input (:meth:`CacheStore.delta_lookup`)
serves the cached report bytes and re-enters the run as a ``--resume``
over them, recomputing only the last cached record and the appended
tail.  ``--many2many`` entries record per-target ``(digest, score)``
values in the manifest (``m2m`` block), so a superset target set reuses
every cached score and dispatches only the delta targets
(:meth:`CacheStore.m2m_scan`).  Every delta serve reads through the
same CRC discipline as an exact hit — a rotted index or blob is a
plain miss, never a corrupt splice — and is accounted FRACTIONALLY
(records served / records total, :meth:`CacheStore.note_delta`), so
``pwasm_cache_hit_ratio`` stays truthful about work actually saved.  Eviction is LRU (manifest mtime = last access) under
``--result-cache-max-bytes`` plus optional TTL; all byte accounting
runs through one lock-guarded :class:`ByteLedger` shared with the
daemon's result spool, so ``pwasm_cache_bytes`` and
``pwasm_service_spool_bytes`` cannot drift from disk under concurrent
evictions.

Like every ``pwasm_tpu/service/`` module this file is jax-free
(``qa/check_supervision.py::find_cache_violations`` additionally
requires it to EXIST — the serving tiers all lean on it).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib

CACHE_KEY_VERSION = 1

# ---------------------------------------------------------------------------
# flag canonicalization table (docs/SERVICE.md "Result cache" section;
# the matrix is unit-tested in tests/test_cache.py)
# ---------------------------------------------------------------------------

# result-affecting booleans: present/absent changes output bytes
KEYED_BOOL = frozenset((
    "G", "F", "C", "N",            # analysis mode selection
    "realign",                     # rewrites gap structures
    "remove-cons-gaps",            # consensus refinement policy
    "no-refine-clip",              # clip refinement policy
    "skip-bad-lines",              # changes which records emit rows
    "many2many",                   # a different job type entirely
))

# result-affecting valued flags: the VALUE is keyed verbatim
KEYED_VALUE = frozenset((
    "c",                           # clipmax
    "band",                        # DP band (realign / many2many)
))

# result-affecting FILE flags: keyed by the file's content digest,
# not its path (the same motif set under a new name still hits)
KEYED_FILE = frozenset(("motifs",))

# output selectors: the KIND is keyed (an entry holds exactly the
# kinds its jobs request), the PATH is not
OUTPUT_KINDS = ("o", "s", "w", "ace", "info", "cons")

# provably byte-neutral (the repo's parity contracts) or pure
# per-invocation plumbing: never part of the key
EXCLUDED = frozenset((
    "v", "D",                      # verbosity (stderr only)
    "d", "p", "m",                 # parsed-but-unread reference quirks
    "device", "batch", "shard",    # placement: bytes are parity-gated
    "max-retries", "device-deadline", "deadline-s", "fallback",
    "recover", "reprobe-interval", "reprobe-max",
    "profile", "stats", "trace-json", "log-json",
    "log-json-max-bytes", "trace-max-events", "metrics-textfile",
    "compile-cache-dir",
    "result-cache", "result-cache-max-bytes",
))

# inherently uncacheable semantics: their presence BYPASSES the cache
BYPASS = frozenset(("resume", "follow", "inject-faults"))


class Classified:
    """The canonical view of one job argv the key derives from."""

    __slots__ = ("flag_items", "output_kinds", "output_paths",
                 "ref_path", "input_path", "motif_path", "many2many")

    def __init__(self, flag_items, output_kinds, output_paths,
                 ref_path, input_path, motif_path, many2many):
        self.flag_items = flag_items        # sorted (flag, value) rows
        self.output_kinds = output_kinds    # sorted kind names
        self.output_paths = output_paths    # kind -> path (this job's)
        self.ref_path = ref_path
        self.input_path = input_path
        self.motif_path = motif_path
        self.many2many = many2many


def classify(opts: dict, positional: list) -> Classified | None:
    """Canonicalize a parsed argv (``cli._parse_args`` output) into
    the key's flag view, or ``None`` when the job must bypass the
    cache (bypass flag, unknown flag, stdin input, stdout report).
    Pure — no file reads happen here."""
    if any(k in opts for k in BYPASS):
        return None
    flag_items: list[tuple[str, str]] = []
    output_paths: dict[str, str] = {}
    motif_path = None
    for k, v in opts.items():
        if k in EXCLUDED:
            continue
        if k in KEYED_BOOL:
            if v is True or v:          # --flag or --flag=anything
                flag_items.append((k, ""))
            continue
        if k in KEYED_VALUE:
            if v is True:
                return None             # malformed: let the run reject
            flag_items.append((k, str(v)))
            continue
        if k in KEYED_FILE:
            if v is True:
                return None
            motif_path = str(v)
            continue
        if k in OUTPUT_KINDS:
            if v is True:
                return None
            output_paths[k] = str(v)
            continue
        if k == "r":
            continue                    # keyed as the ref digest
        return None                     # unknown flag: cannot vouch
    if "o" not in output_paths:
        return None     # a stdout report has no file to serve back
    rpath = opts.get("r")
    if not isinstance(rpath, str) or not rpath:
        return None
    if len(positional) != 1 or positional[0] in ("", "-"):
        return None     # stdin (or no) input: nothing to digest
    flag_items.sort()
    return Classified(
        flag_items=tuple(flag_items),
        output_kinds=tuple(sorted(output_paths)),
        output_paths=output_paths,
        ref_path=rpath,
        input_path=positional[0],
        motif_path=motif_path,
        many2many="many2many" in opts)


def classify_argv(argv: list) -> Classified | None:
    """:func:`classify` over a raw argv (the daemon's admission path
    — the argv is already cwd-absolutized there)."""
    from pwasm_tpu.cli import CliError, _parse_args
    try:
        opts, positional = _parse_args(list(argv))
    except CliError:
        return None
    return classify(opts, positional)


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def digest_file(path: str) -> str:
    """sha256 over a file's raw bytes in one bounded block pass.
    Deliberately NOT mmap-backed: this runs at admission inside the
    serve daemon/router on CLIENT-owned files — touching a mapped
    page past the EOF of a file truncated under us raises SIGBUS and
    kills the whole process, where a ``read`` merely sees a short
    file (the key-drift re-check at insert time catches the change
    either way).  Hashing dominates the pass, not the read."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def fasta_digest(path: str) -> str:
    """Canonicalized FASTA digest: per record, the stripped header and
    the UPPERCASED, whitespace-stripped sequence — cosmetic line
    wrapping, case, or trailing blank lines cannot split the cache,
    while any real sequence or naming change keys a distinct entry."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith(b">"):
                h.update(line)
                h.update(b"\n")
            else:
                h.update(line.upper())
    return h.hexdigest()


def record_digest(name: str, seq) -> str:
    """Canonical digest of ONE FASTA record (the ``--many2many``
    per-CDS section key's query half) — same canonical form as
    :func:`fasta_digest` applied to a single record."""
    h = hashlib.sha256()
    h.update(b">" + str(name).encode("utf-8") + b"\n")
    s = seq if isinstance(seq, (bytes, bytearray)) else \
        str(seq).encode("utf-8")
    h.update(bytes(s).upper())
    return h.hexdigest()


def cache_key(ref_digest: str, input_digest: str, flag_items,
              output_kinds) -> str:
    """The content-addressed key: sha256 over the canonical JSON of
    every result-affecting fact."""
    doc = {"v": CACHE_KEY_VERSION, "ref": ref_digest,
           "input": input_digest,
           "flags": [list(fi) for fi in flag_items],
           "outputs": list(output_kinds)}
    return hashlib.sha256(json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


def section_key(query_digest: str, targets_digest: str,
                band: int) -> str:
    """The ``--many2many`` per-CDS SECTION key: one query record vs
    the whole target set under one band — the granularity that lets a
    job re-scoring 9 cached CDS + 1 new one dispatch only the new
    one."""
    doc = {"v": CACHE_KEY_VERSION, "m2m_section": 1,
           "q": query_digest, "targets": targets_digest,
           "band": int(band)}
    return hashlib.sha256(json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


def family_key(ref_digest: str, flag_items, output_kinds) -> str:
    """The delta FAMILY key: sha256 over the exact key's document
    minus the input digest.  Two runs in one family differ only by
    input CONTENT — exactly the population where a prefix-preserving
    append can be served as a delta instead of a cold run."""
    doc = {"v": CACHE_KEY_VERSION, "family": 1, "ref": ref_digest,
           "flags": [list(fi) for fi in flag_items],
           "outputs": list(output_kinds)}
    return hashlib.sha256(json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


def m2m_family_key(query_digest: str, band: int) -> str:
    """The ``--many2many`` delta family: one query record under one
    band, whatever the target set — superset reuse matches per-target
    digests inside the family, so the band stays keyed (a different
    band is different scores, never spliced)."""
    doc = {"v": CACHE_KEY_VERSION, "m2m_family": 1,
           "q": query_digest, "band": int(band)}
    return hashlib.sha256(json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


def derive_key(cls: Classified,
               input_digest: str | None = None) -> str | None:
    """Digest the classified job's inputs and derive its cache key;
    ``None`` when any input is unreadable (the run will produce the
    real diagnostic — a cache must never pre-empt it).
    ``input_digest`` skips the input re-read when the caller already
    holds it — the ingest reader's digest rides its single pass
    (``stream.pafstream.BlockLineReader``), and the insert side uses
    it both to avoid a second read and to PROVE the input did not
    change between keying and running (key mismatch = no insert)."""
    derived = derive_keys(cls, input_digest=input_digest)
    return None if derived is None else derived[0]


def derive_keys(cls: Classified,
                input_digest: str | None = None
                ) -> tuple[str, str] | None:
    """:func:`derive_key` plus the entry's delta FAMILY key, from one
    digest pass: ``(exact_key, family)`` or ``None``."""
    try:
        ref_d = fasta_digest(cls.ref_path)
        input_d = input_digest if input_digest is not None else (
            fasta_digest(cls.input_path) if cls.many2many
            else digest_file(cls.input_path))
        flag_items = list(cls.flag_items)
        if cls.motif_path is not None:
            flag_items.append(("motifs#sha256",
                               digest_file(cls.motif_path)))
            flag_items.sort()
    except OSError:
        return None
    return (cache_key(ref_d, input_d, flag_items, cls.output_kinds),
            family_key(ref_d, flag_items, cls.output_kinds))


# a delta index over a multi-million-line assembly would cost more to
# scan than the delta saves; entries past the cap still serve exact
# hits, they just never delta-match
DELTA_MAX_LINES = 100_000


def delta_eligible(cls: Classified) -> bool:
    """True when a near-miss for this job may be served as a delta:
    report-only output (the ``--resume`` fast path that makes the
    serve cheap is parse-only — MSA/summary builds need the prefix
    records re-inserted) and strict per-line replay semantics (no
    ``--skip-bad-lines``: the fast path does not re-validate the
    served prefix)."""
    return (not cls.many2many
            and cls.output_kinds == ("o",)
            and all(k != "skip-bad-lines" for k, _ in cls.flag_items))


def paf_line_digests(path: str, max_lines: int = DELTA_MAX_LINES
                     ) -> tuple[list[str] | None, str | None]:
    """The delta index column for one PAF input: one 16-hex sha256
    prefix per line (terminator-stripped, so a missing final newline
    cannot split a prefix match), plus the whole-file sha256 from the
    same pass (the caller proves the file it indexed is the file that
    ran).  ``(None, digest)`` when the file exceeds ``max_lines``;
    ``(None, None)`` when unreadable."""
    out: list[str] | None = []
    h = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            for raw in f:
                h.update(raw)
                if out is not None:
                    if len(out) >= max_lines:
                        out = None
                    else:
                        out.append(hashlib.sha256(
                            raw.rstrip(b"\r\n")).hexdigest()[:16])
    except OSError:
        return None, None
    return out, h.hexdigest()


def classify_stream(opts: dict) -> Classified | None:
    """:func:`classify` for a SOCKET-fed stream job (ROADMAP 4c) —
    same flag walk, no positional: the input arrives as frames, so its
    identity is the per-line digest column, not a file digest."""
    cls = classify(opts, ["<stream>"])
    if cls is None:
        return None
    cls.input_path = None
    return cls


def line_digest(line: str) -> str:
    """One stream line's delta-index digest — the same 16-hex column
    :func:`paf_line_digests` derives from a file, so stream and file
    entries of one family delta-match each other (terminator-stripped
    on both sides)."""
    return hashlib.sha256(
        line.rstrip("\r\n").encode("utf-8")).hexdigest()[:16]


def stream_keys(cls: Classified,
                digests: list) -> tuple[str, str] | None:
    """``(exact_key, family)`` for a stream job whose input is the
    given line-digest column.  The FAMILY is byte-identical to the
    file-side :func:`derive_keys` family for the same ref/flags/
    outputs — that shared namespace is what lets a re-opened stream
    delta-hit an entry a file run inserted, and vice versa.  The exact
    key hashes the digest column itself (there is no input file to
    digest), so stream entries still exact-collide with byte-identical
    stream replays."""
    try:
        ref_d = fasta_digest(cls.ref_path)
        flag_items = list(cls.flag_items)
        if cls.motif_path is not None:
            flag_items.append(("motifs#sha256",
                               digest_file(cls.motif_path)))
            flag_items.sort()
    except OSError:
        return None
    input_d = "stream:" + hashlib.sha256(
        "".join(digests).encode("ascii")).hexdigest()
    return (cache_key(ref_d, input_d, flag_items, cls.output_kinds),
            family_key(ref_d, flag_items, cls.output_kinds))


# ---------------------------------------------------------------------------
# the unified byte ledger (spool + cache accounting)
# ---------------------------------------------------------------------------

class ByteLedger:
    """One lock-guarded byte ledger with named accounts.  The daemon
    charges its result spool and its result cache against the SAME
    ledger, so the two byte gauges are read from one synchronized
    source and cannot drift from disk under concurrent evictions (the
    latent window the old bare ``_spool_bytes`` int left open around
    replay-time increments)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._accounts: dict[str, int] = {}

    def add(self, account: str, n: int) -> None:
        with self._lock:
            self._accounts[account] = \
                self._accounts.get(account, 0) + int(n)

    def sub(self, account: str, n: int) -> None:
        with self._lock:
            self._accounts[account] = max(
                0, self._accounts.get(account, 0) - int(n))

    def set(self, account: str, n: int) -> None:
        with self._lock:
            self._accounts[account] = max(0, int(n))

    def value(self, account: str) -> int:
        with self._lock:
            return self._accounts.get(account, 0)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

MANIFEST_VERSION = 1
_ACCOUNT = "cache"

# files younger than this are never sweep candidates: on a shared dir
# a sibling's in-flight insert is indistinguishable from a crash
# remnant until its manifest commits
SWEEP_GRACE_S = 60.0


class CacheStore:
    """Content-addressed result store (module docstring for layout and
    crash/rot semantics).  Thread-safe: admission (connection threads),
    workers (insert at finish) and eviction share one lock.

    ``metrics`` is the ``build_cache_metrics`` dict (obs/catalog.py);
    ``ledger`` the shared :class:`ByteLedger` (one is created when the
    caller has none)."""

    def __init__(self, root: str, max_bytes: int | None = None,
                 ttl_s: float | None = None, metrics: dict | None = None,
                 ledger: ByteLedger | None = None):
        self.root = root
        self.max_bytes = max_bytes
        self.ttl_s = ttl_s
        self.metrics = metrics or {}
        self.ledger = ledger if ledger is not None else ByteLedger()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.insert_errors = 0   # failed inserts (ENOSPC and kin):
        #   the degrade-to-pass-through counter — the job was served,
        #   only the cache write was skipped (ISSUE 18 satellite)
        self.evictions = 0
        self.delta_hits = 0
        self.delta_records_served = 0
        self.delta_records_total = 0
        self._delta_fraction = 0.0   # sum of served/total per delta
        self.prefetched = 0
        self._recounted_at = 0.0     # monotonic, last disk recount
        from pwasm_tpu.utils.fsio import ensure_private_dir
        ensure_private_dir(root)
        self.sweep()

    # ---- internals -----------------------------------------------------
    def _manifest_path(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def _blob_path(self, key: str, kind: str) -> str:
        return os.path.join(self.root, f"{key}.{kind}")

    def _read_manifest(self, key: str) -> dict | None:
        """Parse + CRC-verify one manifest; None on any defect (the
        ckpt-v2 rule: torn or rotted state is absent state)."""
        from pwasm_tpu.utils.fsio import payload_crc
        try:
            with open(self._manifest_path(key),
                      encoding="utf-8") as f:
                obj = json.load(f)
            if not isinstance(obj, dict):
                raise ValueError("not an object")
            crc = int(obj.pop("crc"))
            if payload_crc(obj) != crc:
                raise ValueError("manifest CRC mismatch")
            if obj.get("version") != MANIFEST_VERSION \
                    or obj.get("key") != key \
                    or not isinstance(obj.get("outputs"), dict):
                raise ValueError("manifest schema mismatch")
        except (OSError, ValueError, KeyError, TypeError):
            return None
        return obj

    def _entry_bytes(self, manifest: dict) -> int:
        return int(manifest.get("bytes", 0))

    def _drop_locked(self, key: str, manifest: dict | None) -> None:
        """Unlink one entry (manifest first — later lookups miss even
        if a blob unlink fails).  The caller owes ONE
        ``_recount_locked`` after its whole drop batch — per-drop
        recounts would make eviction O(drops x dir_size) under the
        lock admission lookups need."""
        try:
            os.unlink(self._manifest_path(key))
        except OSError:
            pass
        kinds = (manifest or {}).get("outputs") or {}
        for kind in list(kinds) or list(OUTPUT_KINDS):
            try:
                os.unlink(self._blob_path(key, kind))
            except OSError:
                pass
        try:            # the delta-index sidecar dies with its entry
            os.unlink(self._blob_path(key, "dx"))
        except OSError:
            pass

    def _recount_locked(self) -> None:
        """Refresh the ledger's cache account from what is ACTUALLY on
        disk (sum of file sizes in the cache dir).  Counting from disk
        rather than incrementally is what keeps the gauge truthful on
        a SHARED cache dir, where sibling fleet members insert and
        evict under us."""
        total = 0
        try:
            for n in os.listdir(self.root):
                try:
                    total += os.path.getsize(
                        os.path.join(self.root, n))
                except OSError:
                    pass
        except OSError:
            return
        self._recounted_at = time.monotonic()
        self.ledger.set(_ACCOUNT, total)
        self._publish()

    def _publish(self) -> None:
        """Refresh the gauges from the ledger + counters."""
        m = self.metrics
        if not m:
            return
        g = m.get("bytes")
        if g is not None:
            g.set(self.ledger.value(_ACCOUNT))
        ratio = m.get("hit_ratio")
        if ratio is not None:
            total = self.hits + self.misses
            # delta serves count FRACTIONALLY (records served /
            # records total); their exact lookups already sit in the
            # miss denominator
            ratio.set(round((self.hits + self._delta_fraction)
                            / total, 6) if total else 0.0)

    def _count(self, what: str) -> None:
        setattr(self, what, getattr(self, what) + 1)
        c = self.metrics.get({"hits": "hits", "misses": "misses",
                              "insertions": "insertions",
                              "insert_errors": "insert_errors",
                              "evictions": "evictions"}[what])
        if c is not None:
            c.inc()
        self._publish()

    # ---- public API ----------------------------------------------------
    def sweep(self) -> None:
        """Startup consistency pass: remove orphan blobs (a kill -9
        landed between blob writes and the manifest commit — the
        insert never durably happened) and rebuild the ledger's byte
        account from what is actually on disk.  Only files OLDER than
        :data:`SWEEP_GRACE_S` are candidates: on a SHARED fleet dir a
        sibling process's in-flight insert looks exactly like a crash
        remnant (blobs and ``.tmp`` files, no manifest yet) and must
        never be reaped mid-write — a real crash's leavings age past
        the window and the next sweep gets them.  Manifests whose
        blobs rotted or vanished are handled LAZILY by :meth:`get`
        (drop + evict + miss), so a sweep never pays a CRC read of
        every entry."""
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return
            now = time.time()
            manifests = {n[:-5] for n in names if n.endswith(".json")}
            for n in sorted(names):
                if n.endswith(".json"):
                    continue
                key = n.rsplit(".", 1)[0]
                if key in manifests:
                    continue
                path = os.path.join(self.root, n)
                try:
                    if now - os.path.getmtime(path) < SWEEP_GRACE_S:
                        continue     # possibly a sibling's in-flight
                        #              insert — never reap mid-write
                    os.unlink(path)
                except OSError:
                    pass
            self._recount_locked()

    def contains(self, key: str) -> bool:
        """Cheap probe (the ``cache-probe`` verb): a CRC-valid,
        unexpired manifest exists.  Blobs are verified at serve time."""
        with self._lock:
            manifest = self._read_manifest(key)
            if manifest is None:
                return False
            if self._expired(manifest):
                return False
            return True

    def _expired(self, manifest: dict) -> bool:
        if self.ttl_s is None:
            return False
        created = manifest.get("created")
        if not isinstance(created, (int, float)):
            return True
        return time.time() - created > self.ttl_s

    def _read_blobs_locked(self, key: str,
                           manifest: dict) -> dict | None:
        """Read + CRC-verify every blob of one entry; None on any
        defect (the caller owns the drop/accounting policy)."""
        blobs: dict[str, bytes] = {}
        for kind, meta in manifest["outputs"].items():
            try:
                with open(self._blob_path(key, kind), "rb") as f:
                    data = f.read()
                if len(data) != int(meta["bytes"]) \
                        or zlib.crc32(data) != int(meta["crc"]):
                    raise ValueError("blob CRC mismatch")
            except (OSError, ValueError, KeyError, TypeError):
                return None
            blobs[kind] = data
        return blobs

    def get(self, key: str) -> tuple[dict, dict] | None:
        """Serve one entry: ``(manifest, {kind: bytes})`` with every
        blob CRC-verified, or ``None`` (counted as a miss).  Any
        defect — rot, truncation, expiry — DROPS the entry: a corrupt
        entry is served exactly never."""
        with self._lock:
            manifest = self._read_manifest(key)
            if manifest is None:
                self._count("misses")
                return None
            if self._expired(manifest):
                self._drop_locked(key, manifest)
                self._recount_locked()
                self._count("evictions")
                self._count("misses")
                return None
            blobs = self._read_blobs_locked(key, manifest)
            if blobs is None:
                # rot destroys the entry: counted as an EVICTION
                # too (the metric's documented causes include CRC
                # rot — churn must be visible to cache_thrash)
                self._drop_locked(key, manifest)
                self._recount_locked()
                self._count("evictions")
                self._count("misses")
                return None
            try:
                # LRU clock: manifest mtime = last access
                os.utime(self._manifest_path(key))
            except OSError:
                pass
            self._count("hits")
            return manifest, blobs

    def delta_lookup(self, family: str, digests: list[str],
                     allow_equal: bool = False
                     ) -> tuple[str, dict, dict, int] | None:
        """Find the best delta candidate for a near-miss: a CRC-whole
        entry in the same FAMILY whose recorded input is a (strict,
        unless ``allow_equal``) per-line prefix of the new input's
        ``digests``.  Longest prefix wins — it leaves the smallest
        tail to recompute.  Returns ``(key, manifest, blobs,
        cached_lines)`` with every blob CRC-verified exactly like
        :meth:`get`, or ``None``.  A rotted delta INDEX skips the
        candidate (the entry still serves exact hits); rotted BLOBS
        drop the entry like a hit-path read would — either way the
        answer degrades to a miss, never a corrupt splice.  Does not
        count hits/misses itself: the caller's exact :meth:`get`
        already counted the miss, and :meth:`note_delta` records the
        fractional outcome."""
        if not digests:
            return None
        blob = "".join(digests).encode("ascii")
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return None
            rows = []
            for n in sorted(names):
                if not n.endswith(".json"):
                    continue
                key = n[:-5]
                m = self._read_manifest(key)
                if m is None or self._expired(m):
                    continue
                d = m.get("delta")
                if not isinstance(d, dict) \
                        or d.get("family") != family:
                    continue
                try:
                    nl = int(d["lines"])
                    dxb, dxc = int(d["bytes"]), int(d["crc"])
                except (KeyError, ValueError, TypeError):
                    continue
                if nl < 2 or nl > len(digests) \
                        or (nl == len(digests) and not allow_equal):
                    continue
                rows.append((nl, key, m, dxb, dxc))
            rows.sort(key=lambda r: r[0], reverse=True)
            for nl, key, m, dxb, dxc in rows:
                try:
                    with open(self._blob_path(key, "dx"),
                              "rb") as f:
                        dx = f.read()
                    if len(dx) != dxb or zlib.crc32(dx) != dxc:
                        raise ValueError("delta index CRC mismatch")
                except (OSError, ValueError):
                    continue
                if dx != blob[:len(dx)]:
                    continue    # same family, not a prefix append
                blobs = self._read_blobs_locked(key, m)
                if blobs is None:
                    self._drop_locked(key, m)
                    self._recount_locked()
                    self._count("evictions")
                    continue
                try:
                    os.utime(self._manifest_path(key))
                except OSError:
                    pass
                return key, m, blobs, nl
        return None

    def delta_index(self, family: str) -> list[tuple[int, str]]:
        """Snapshot the family's delta candidates as ``(lines,
        digest_column)`` rows — the stream-delta HOLD path's in-memory
        oracle: per arriving frame it needs to know whether any
        candidate could still prefix-match once more lines arrive,
        without re-walking the store per frame.  CRC-checked dx only
        (a rotted index is simply absent from the snapshot); serving
        still goes through :meth:`delta_lookup`, which re-verifies."""
        out: list[tuple[int, str]] = []
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return out
            for n in sorted(names):
                if not n.endswith(".json"):
                    continue
                key = n[:-5]
                m = self._read_manifest(key)
                if m is None or self._expired(m):
                    continue
                d = m.get("delta")
                if not isinstance(d, dict) \
                        or d.get("family") != family:
                    continue
                try:
                    nl = int(d["lines"])
                    dxb, dxc = int(d["bytes"]), int(d["crc"])
                    with open(self._blob_path(key, "dx"),
                              "rb") as f:
                        dx = f.read()
                    if len(dx) != dxb or zlib.crc32(dx) != dxc:
                        continue
                except (KeyError, ValueError, TypeError, OSError):
                    continue
                if nl >= 2:
                    out.append((nl, dx.decode("ascii", "replace")))
        return out

    def note_delta(self, served: int, total: int) -> None:
        """Record one completed delta serve FRACTIONALLY: a run that
        served 90 cached records of 100 moves the hit ratio by 0.9 of
        a hit, not 0 (the exact lookup already counted its miss) and
        not 1 — ``cache_thrash`` and the ``top`` CACHE row stay
        meaningful under delta traffic."""
        with self._lock:
            self.delta_hits += 1
            self.delta_records_served += max(0, int(served))
            self.delta_records_total += max(0, int(total))
            if total > 0:
                self._delta_fraction += min(
                    1.0, max(0, int(served)) / int(total))
            c = self.metrics.get("delta_hits")
            if c is not None:
                c.inc()
            self._publish()

    def m2m_scan(self) -> list[tuple[str, dict]]:
        """All CRC-valid, unexpired entries carrying an ``m2m`` score
        table — the superset-reuse candidate pool, gathered in ONE
        directory pass per ``--many2many`` job (the caller indexes by
        family)."""
        out: list[tuple[str, dict]] = []
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return out
            for n in sorted(names):
                if not n.endswith(".json"):
                    continue
                m = self._read_manifest(n[:-5])
                if m is None or self._expired(m):
                    continue
                if isinstance(m.get("m2m"), dict):
                    out.append((n[:-5], m))
        return out

    def contains_family(self, family: str) -> bool:
        """Cheap fleet-affinity probe (the ``cache-probe`` verb's
        ``family`` field): does any CRC-valid, unexpired entry carry
        this delta (report prefix) or m2m (target subset) family?
        Manifest reads only — the member that answers true can likely
        serve the job as a DELTA at its own admission."""
        with self._lock:
            try:
                names = os.listdir(self.root)
            except OSError:
                return False
            for n in names:
                if not n.endswith(".json"):
                    continue
                m = self._read_manifest(n[:-5])
                if m is None or self._expired(m):
                    continue
                d = m.get("delta")
                if isinstance(d, dict) and d.get("family") == family:
                    return True
                d = m.get("m2m")
                if isinstance(d, dict) and d.get("family") == family:
                    return True
        return False

    def prefetch(self, max_entries: int) -> int:
        """Warm-spawn cache replication: page the HOTTEST entries
        (manifest mtime = last access, newest first) through a full
        CRC-verified read BEFORE the member takes traffic, so a
        scaler-spawned member's first repeat job serves from a warm
        page cache like a long-lived sibling's.  Non-destructive —
        a rotted entry is skipped (the serving path owns drops) —
        and locked per entry, so a concurrent admission lookup never
        waits behind the whole warm pass.  Returns entries warmed."""
        rows = []
        try:
            for n in os.listdir(self.root):
                if not n.endswith(".json"):
                    continue
                try:
                    rows.append((os.path.getmtime(
                        os.path.join(self.root, n)), n[:-5]))
                except OSError:
                    pass
        except OSError:
            return 0
        rows.sort(reverse=True)
        warmed = 0
        for _t, key in rows[:max(0, int(max_entries))]:
            with self._lock:
                m = self._read_manifest(key)
                if m is None or self._expired(m):
                    continue
                if self._read_blobs_locked(key, m) is not None:
                    warmed += 1
        with self._lock:
            self.prefetched += warmed
            self._publish()
        return warmed

    def insert(self, key: str, outputs: dict[str, bytes],
               stats: dict | None = None,
               delta: dict | None = None,
               extra: dict | None = None) -> bool:
        """Store one entry: blobs first, CRC'd manifest LAST (the
        commit point — a crash at any instant leaves either a whole
        entry or orphan blobs the next sweep removes), then enforce
        the byte budget.  Returns False on any write failure (a full
        disk costs the cache, never the job).

        ``delta`` (``{"family", "lines", "dx": bytes}``) attaches the
        per-line delta index: the ``dx`` sidecar is written with the
        blobs — BEFORE the manifest commit, so a crash can only leave
        a sidecar orphan the sweep reaps, never a manifest pointing at
        a missing index.  ``extra`` merges caller facts (the ``m2m``
        per-target score table) into the CRC'd manifest."""
        from pwasm_tpu.utils.fsio import (payload_crc,
                                          write_durable_bytes,
                                          write_durable_text)
        meta: dict[str, dict] = {}
        total = 0
        wrote_dx = False
        with self._lock:
            try:
                for kind, data in outputs.items():
                    write_durable_bytes(self._blob_path(key, kind),
                                        data)
                    meta[kind] = {"bytes": len(data),
                                  "crc": zlib.crc32(data)}
                    total += len(data)
                manifest = {"version": MANIFEST_VERSION, "key": key,
                            "created": round(time.time(), 3),
                            "outputs": meta, "stats": stats,
                            "bytes": total}
                if extra:
                    manifest.update(extra)
                if delta is not None:
                    dx = delta["dx"]
                    write_durable_bytes(self._blob_path(key, "dx"),
                                        dx)
                    wrote_dx = True
                    total += len(dx)
                    manifest["bytes"] = total
                    manifest["delta"] = {
                        "family": delta["family"],
                        "lines": int(delta["lines"]),
                        "bytes": len(dx), "crc": zlib.crc32(dx)}
                manifest["crc"] = payload_crc(
                    {k: v for k, v in manifest.items() if k != "crc"})
                write_durable_text(self._manifest_path(key),
                                   json.dumps(manifest, sort_keys=True,
                                              separators=(",", ":")))
            except OSError:
                for kind in list(meta) + (["dx"] if wrote_dx else []):
                    try:
                        os.unlink(self._blob_path(key, kind))
                    except OSError:
                        pass
                # degrade to pass-through (ISSUE 18 satellite): the
                # job was served either way — count the skipped
                # insert so a full disk is VISIBLE, never silent
                self._count("insert_errors")
                return False
            # re-inserts (two members racing one job on a shared dir)
            # net out here: bytes are always recounted from disk,
            # never accumulated
            self._recount_locked()
            self._count("insertions")
            self._evict_locked()
        return True

    def _evict_locked(self) -> None:
        """LRU eviction to the ``max_bytes`` budget (manifest mtime =
        last access) + TTL expiry.  One ledger recount for the whole
        pass, however many entries dropped."""
        if self.max_bytes is None and self.ttl_s is None:
            return
        rows = []
        dropped = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for n in sorted(names):
            if not n.endswith(".json"):
                continue
            key = n[:-5]
            manifest = self._read_manifest(key)
            if manifest is None:
                self._drop_locked(key, None)
                dropped += 1
                continue
            if self._expired(manifest):
                self._drop_locked(key, manifest)
                dropped += 1
                self._count("evictions")
                continue
            try:
                mtime = os.path.getmtime(self._manifest_path(key))
            except OSError:
                mtime = 0.0
            rows.append((mtime, key, manifest))
        if self.max_bytes is not None:
            total = sum(self._entry_bytes(m) for _t, _k, m in rows)
            rows.sort()                  # oldest access first
            for _t, key, manifest in rows:
                if total <= self.max_bytes:
                    break
                total -= self._entry_bytes(manifest)
                self._drop_locked(key, manifest)
                dropped += 1
                self._count("evictions")
        if dropped:
            self._recount_locked()

    def evict_now(self) -> None:
        """Run one eviction pass (TTL + budget) outside an insert —
        the daemon's periodic tick calls this so an idle cache still
        expires."""
        with self._lock:
            self._evict_locked()

    def stats_dict(self) -> dict:
        """The svc-stats ``cache`` block.  Bytes are recounted from
        disk (a shared dir's siblings mutate it under us) but
        TIME-GATED: a `top` refresh loop hammering the stats verb on
        a huge cache dir must not serialize every admission lookup
        behind a directory scan — between recounts the last-known
        ledger value (maintained by this process's own mutations)
        serves."""
        with self._lock:
            if time.monotonic() - self._recounted_at > 2.0:
                self._recount_locked()
            total = self.hits + self.misses
            return {
                "enabled": True,
                "dir": self.root,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "insertions": self.insertions,
                "insert_errors": self.insert_errors,
                "evictions": self.evictions,
                "delta_hits": self.delta_hits,
                "delta_records_served": self.delta_records_served,
                "delta_records_total": self.delta_records_total,
                "prefetched": self.prefetched,
                "bytes": self.ledger.value(_ACCOUNT),
                "hit_ratio": round(
                    (self.hits + self._delta_fraction) / total, 6)
                if total else 0.0,
            }


# ---------------------------------------------------------------------------
# serving helpers (shared by the CLI, the daemon and the router)
# ---------------------------------------------------------------------------

def insert_from_paths(store: CacheStore, key: str, cls: Classified,
                      input_digest: str | None = None,
                      stats: dict | None = None) -> bool:
    """Insert a completed run's output FILES under ``key`` — the ONE
    populate implementation every tier shares (cold CLI after
    ``_main_loop``, daemon at job finish).  The key is RE-derived
    first (``input_digest`` reuses the ingest reader's ride-along
    digest so no input re-read happens): an input rewritten while the
    run was in flight drifts the key, and inserting the new outputs
    under the OLD key would poison every future hit — skipping is
    always safe.  Best-effort: False on drift or any read failure."""
    try:
        delta = None
        if delta_eligible(cls):
            # the per-line delta index, from one extra input pass —
            # attached only when that pass reads the SAME bytes the
            # run keyed (whole-file digest match), so a mid-flight
            # rewrite can never bind a stale index to fresh outputs
            digests, fdig = paf_line_digests(cls.input_path)
            if digests is not None and len(digests) >= 2 \
                    and input_digest in (None, fdig):
                if input_digest is None:
                    input_digest = fdig
                delta = {"lines": len(digests),
                         "dx": "".join(digests).encode("ascii")}
        derived = derive_keys(cls, input_digest=input_digest)
        if derived is None or derived[0] != key:
            return False
        if delta is not None:
            delta["family"] = derived[1]
        blobs = {}
        for kind, path in cls.output_paths.items():
            with open(path, "rb") as f:
                blobs[kind] = f.read()
    except OSError:
        return False
    if isinstance(stats, dict):
        # the delta markers describe THIS run's serve, not the entry:
        # a future exact hit replaying them would claim a delta that
        # never happened
        stats = {k: v for k, v in stats.items()
                 if k not in ("cache_delta", "cache_records_served",
                              "cache_records_total")}
    else:
        stats = None
    return store.insert(key, blobs, stats=stats, delta=delta)


def serve_outputs(blobs: dict[str, bytes],
                  paths: dict[str, str]) -> bool:
    """Write the cached output bytes to this invocation's output
    paths.  All-or-nothing precheck: every requested kind must exist
    in the entry (guaranteed when the key includes the kind set, but
    verified anyway) — a partial serve would be worse than a miss."""
    if any(kind not in blobs for kind in paths):
        return False
    for kind, path in paths.items():
        with open(path, "wb") as f:
            f.write(blobs[kind])
    return True


def hit_stats(manifest: dict) -> dict:
    """The ``--stats`` JSON a cache hit serves: the original run's
    stats with ``cache_hit`` set and the backend block ZEROED — this
    serve paid no probe and touched no device, and the acceptance
    gates read exactly that."""
    st = manifest.get("stats")
    st = dict(st) if isinstance(st, dict) else {}
    st["cache_hit"] = True
    st["backend"] = {"probes": 0, "warm_hits": 0}
    return st


def argv_stats_path(argv) -> str | None:
    """The ``--stats=FILE`` path in a job argv, if any — what a hit
    still owes the caller as a file artifact."""
    return next((a.split("=", 1)[1] for a in argv
                 if isinstance(a, str) and a.startswith("--stats=")),
                None)


def write_hit_stats(manifest: dict, stats_path: str | None,
                    strict: bool = False) -> dict:
    """Serve the hit-shaped stats: returns :func:`hit_stats` and, when
    the job asked for a ``--stats`` file, writes it there too — ONE
    implementation for all three serving tiers (CLI / daemon /
    router), so the artifact cannot drift between them.  A failed
    write is swallowed unless ``strict`` (the cold CLI raises its
    canonical diagnostic; the daemons keep serving)."""
    st = hit_stats(manifest)
    if stats_path:
        try:
            with open(stats_path, "w") as f:
                json.dump(st, f, indent=1)
                f.write("\n")
        except OSError:
            if strict:
                raise
    return st
