"""The serve wire protocol: newline-delimited JSON over a unix socket.

One request frame per line, one response frame per line — a format a
shell one-liner (``printf ... | nc -U``) can speak, trivially
greppable in a packet capture, and with no length-prefix framing to
get subtly wrong on either side.  Every frame is a single JSON object
terminated by ``\\n``; a frame longer than :data:`MAX_FRAME_BYTES` is
rejected (``frame_too_large``) and the connection closed, because once
a reader has consumed a partial oversized line the stream can no
longer be resynchronized safely.

Commands (``{"cmd": ...}``):

=============  ==========================================================
``submit``     ``{"cmd":"submit","args":[...cli argv...],
               "cwd":ABS_DIR[,"client":NAME,"priority":LANE]}`` —
               enqueue a report job; relative paths in ``args``
               resolve against the client's ``cwd`` (what a cold run
               would do), never the daemon's.  ``client`` overrides
               the fair-share identity (default: the kernel-attested
               socket-peer uid); ``priority`` names a
               ``--priority-lanes`` tier.  Admission control answers
               ``queue_full`` (the 429 of this protocol: back off and
               retry — the frame carries ``retry_after_s``,
               ``client`` and ``client_depth``) once THAT client's
               queue quota (or the global backstop) fills, and
               ``draining`` once a drain began.  Jobs must write
               their outputs to files (``-o`` required): the socket
               carries control, not bulk report bytes.
``stream``     ``{"cmd":"stream","args":[...cli argv...],
               "cwd":ABS_DIR[,"client":NAME,"priority":LANE]}`` —
               admit a STREAMING-INGESTION job (docs/STREAMING.md):
               same validation and fair-share admission as
               ``submit``, but the argv must carry NO positional PAF
               — the records arrive later as ``stream-data`` frames.
``stream-data``  ``{"cmd":"stream-data","job_id":...,"data":TEXT}`` —
               feed a chunk of PAF text to a stream job.  Chunks may
               split records anywhere (the daemon reassembles lines
               across frames).  Answers ``queue_full`` when the
               stream's buffered-record quota (``--stream-buffer``)
               or its fair share of the global ceiling is exceeded:
               back off ``retry_after_s``-seeded capped-exponential
               and RESEND THE SAME FRAME (admission is all-or-nothing
               per frame, so a rejected frame left no partial state).
``stream-end``   ``{"cmd":"stream-end","job_id":...}`` — no more
               records; the job finishes its tail (MSA/summary) and
               lands terminal.  Follow with ``result`` to wait.
``status``     ``{"cmd":"status","job_id":...}`` — non-blocking state.
``inspect``    ``{"cmd":"inspect","job_id":...}`` — the job's FLIGHT
               RECORD (docs/OBSERVABILITY.md): trace_id,
               phase-accounted walls (queue wait, lease wait, exec —
               per-flush device/host/format breakdown inside) and the
               bounded event ring.  Served from daemon RAM for live
               jobs and from the CRC-verified result spool once the
               result moved to disk.
``result``     ``{"cmd":"result","job_id":...[,"wait":bool,
               "timeout":s]}`` — the terminal verdict (rc, per-job
               RunStats, stderr tail); by default blocks until the job
               finishes.
``cancel``     queued job: removed immediately; running job: a graceful
               drain is requested — the job stops at its next batch
               boundary, leaving a valid resumable checkpoint.
``stats``      the service-level counters (versioned schema).
``cache-probe``  ``{"cmd":"cache-probe","key":SHA256}`` — would this
               daemon's result cache (``serve --result-cache``,
               docs/SERVICE.md) answer the content-addressed key?
               ``{"hit":bool,"enabled":bool}`` from a cheap manifest
               check (no blob reads, no admission).  The fleet router
               uses it for cache-affinity placement: a member that
               already answered a job gets its repeat.
``health``     the self-monitoring verdict (ISSUE 14): ok/degraded/
               failing, the firing SLO rules (docs/OBSERVABILITY.md
               rule catalog) and canary state; a fleet router folds
               every member's verdict into one fleet verdict.
               Surfaced by ``pwasm-tpu health [--exit-code]``.
``logs``       filter the server's ``--log-json`` NDJSON event log
               (rotated ``.1`` generation included) by
               ``filter_trace_id`` / ``job_id`` / ``event``, newest
               ``limit`` (default 1000, max 10000) matches returned
               oldest-first.  (The filter field is ``filter_trace_id``
               because every frame already carries the CONNECTION's
               own ``trace_id``.)
``drain``      begin the same graceful drain a SIGTERM triggers: reject
               new submissions, finish in-flight jobs at batch
               boundaries, mark queued jobs preempted-resumable, exit
               75.
``lease-grant``  ``{"cmd":"lease-grant","epoch":N,"ttl_s":S}`` — grant
               (or heartbeat) the member's epoch lease (ISSUE 16,
               docs/FLEET.md fencing).  The fleet router normally
               piggybacks the same ``{"lease":{"epoch":N,"ttl_s":S}}``
               object on its ``stats`` polls instead of spending a
               round-trip on this verb.  A grant at an epoch LOWER
               than the member has already seen answers ``fenced`` —
               a stale router cannot re-arm a member the fleet moved
               past.  An accepted grant clears a standing self-fence.
``fence``      ``{"cmd":"fence"[,"reason":TEXT]}`` — fence the member
               NOW: in-flight jobs are preempted at their next batch
               boundary (valid resumable ckpt, rc 75, exactly like a
               drain) and new ``submit``/``stream``/``stream-data``
               frames answer the ``fenced`` error until a lease grant
               at the current-or-newer epoch un-fences it.  The same
               transition fires autonomously when a governed lease's
               TTL expires unheartbeated (self-fencing: a partitioned
               member stops writing BEFORE a sibling's ``--resume``
               starts).
``ping``       liveness + protocol version.
=============  ==========================================================

Error responses are ``{"ok": false, "error": <code>, "detail": ...}``
with codes from the ``ERR_*`` constants below.

Trace propagation (ISSUE 11): every request frame MAY carry a
``trace_id`` field (short identifier, ``[A-Za-z0-9_.:@/-]{1,64}``);
``ServiceClient`` mints one per connection and sends it on every
frame.  The ``submit``/``stream`` handlers stamp it onto the admitted
job — journal record, event-log lines, flight record, both sides'
Chrome traces — and echo it in the ok frame; a frame without one gets
a daemon-minted id, so every job is trace-correlatable either way.

Deadline propagation (ISSUE 18, docs/RESILIENCE.md): a ``submit``/
``stream`` frame MAY carry ``deadline_ms`` — the REMAINING end-to-end
budget in integer milliseconds, minted by ``ServiceClient`` from
``--deadline-s`` and re-stamped at each hop with the time already
spent subtracted (the router subtracts its queue/spill time before
forwarding, the daemon subtracts queue + lease wait before exec, the
supervisor enforces it at batch boundaries).  A frame whose budget is
already spent answers ``deadline_exceeded`` without admission; a job
whose budget expires mid-run stops at its next durable checkpoint and
lands terminal ``deadline_exceeded`` (rc 75, resumable — the journal
records the truth).  A frame WITHOUT ``deadline_ms`` behaves exactly
as before this field existed.

Transports and identity (ISSUE 13, docs/FLEET.md): the same frames
run over the unix socket and over TCP (``serve --listen=HOST:PORT``,
``route``).  A frame MAY carry a ``client_token`` field: on TCP —
where no kernel-attested ``SO_PEERCRED`` identity exists — the
daemon buckets the submit under ``tok:<token>`` for fair share, so
identities stay attested-or-explicit on both transports (an explicit
``client`` field still wins; an untokened TCP frame shares the
anonymous bucket).  On an mTLS listener (ISSUE 19,
``--tls-client-ca``) the verified client certificate's CN arrives as
the connection's ``cn:<name>`` peer identity and outranks
``client_token`` — attested cryptography beats a free-form field.

Authorization (ISSUE 19, ``--auth-tokens``): when a scoped-token file
is configured, each frame's credentials (its ``client_token``, or the
connection's mTLS CN principal) must carry the scope its verb
requires; a refused frame answers the ``unauthorized`` error having
changed NO queue/journal/lease state.  Without the flag every verb
stays open — byte-identical to the pre-auth protocol.
"""

from __future__ import annotations

import json

PROTOCOL_VERSION = 1

# one frame = one JSON line.  8 MiB is far above any control payload
# (a submit carries argv, not report bytes) while still bounding what a
# misbehaving client can make the daemon buffer.
MAX_FRAME_BYTES = 8 << 20

# error vocabulary (the "HTTP status codes" of the protocol)
ERR_QUEUE_FULL = "queue_full"        # admission control: back off+retry
ERR_DRAINING = "draining"            # drain in progress: no new jobs
ERR_BAD_JSON = "bad_json"            # unparseable frame (conn survives)
ERR_FRAME_TOO_LARGE = "frame_too_large"  # conn closed: stream unsynced
ERR_BAD_REQUEST = "bad_request"      # parsed, but semantically invalid
ERR_UNKNOWN_CMD = "unknown_cmd"
ERR_UNKNOWN_JOB = "unknown_job"
ERR_FENCED = "fenced"                # epoch-lease fence: member must
#   not accept work (lost/expired lease, or a stale-epoch grant was
#   refused).  Clients treat it like draining: go elsewhere.
ERR_DEADLINE_EXCEEDED = "deadline_exceeded"  # the job's end-to-end
#   deadline budget (submit/stream --deadline-s) ran out: either
#   refused at admission (budget already spent in queues upstream) or
#   landed terminal mid-run at the next batch boundary — rc 75 with a
#   valid resumable checkpoint, so the CLIENT decides whether to
#   resume with a fresh budget or abandon.
ERR_OVERLOADED = "overloaded"        # brownout shedding at the fleet
#   router: fleet-wide queue pressure crossed the SLO threshold and
#   this frame's priority lane is being shed (lowest lane first,
#   hysteresis-damped).  The frame carries retry_after_s; back off
#   like queue_full — but unlike queue_full, no member was asked.
#   Per-client rate limiting (ISSUE 19, --rate-limit) answers the
#   same code with a truthful retry_after_s: to the client the two
#   are the same instruction — slow down.
ERR_UNAUTHORIZED = "unauthorized"    # scoped capability tokens
#   (ISSUE 19, --auth-tokens): the frame's credentials do not carry
#   the scope its verb requires (admin for drain/lease-grant/fence,
#   ownership-or-admin for cancel, submit/read for the data plane).
#   The refusal happens BEFORE admission: no queue, journal or lease
#   state changed.  Not retryable with the same credentials.


class FrameError(Exception):
    """A frame-level protocol violation.  ``code`` is the ``ERR_*``
    wire code; ``fatal`` says whether the connection can keep being
    used (a malformed JSON line is recoverable — the next line is a
    fresh frame; an oversized line is not, the reader lost sync)."""

    def __init__(self, code: str, detail: str, fatal: bool = False):
        super().__init__(detail)
        self.code = code
        self.fatal = fatal


def resolve_client_identity(req: dict, peer: str | None) -> str:
    """The fair-share identity resolution order, attested-or-explicit
    on BOTH transports (one function shared by the serve daemon and
    the fleet router, so their quota/DRR bucketing can never drift):
    an explicit ``client`` field wins; else an mTLS-attested peer
    certificate CN (the connection's ``cn:<name>`` peer string —
    verified cryptography outranks any free-form frame field); else a
    ``client_token`` frame field buckets as ``tok:<token>`` (the
    plaintext-TCP identity — AF_INET has no SO_PEERCRED); else the
    kernel-attested unix peer uid; else the anonymous bucket."""
    client = req.get("client")
    if client is not None:
        return client
    if isinstance(peer, str) and peer.startswith("cn:"):
        return peer
    tok = req.get("client_token")
    if isinstance(tok, str) and tok:
        return "tok:" + tok
    return peer or ""


def parse_deadline_ms(req: dict) -> tuple[int | None, dict | None]:
    """Parse the optional ``deadline_ms`` admission-frame field, one
    implementation shared by the serve daemon and the fleet router (so
    the validation and the spent-budget refusal cannot drift): returns
    ``(budget, None)`` — budget ``None`` when the frame carries no
    deadline — or ``(None, error_frame)``.  A malformed budget is a
    ``bad_request``; a present-but-spent one (``<= 0``) answers
    ``deadline_exceeded`` WITHOUT admitting anything — the truthful
    refusal: upstream hops already ate the whole budget."""
    v = req.get("deadline_ms")
    if v is None:
        return None, None
    if isinstance(v, bool) or not isinstance(v, int):
        return None, err(ERR_BAD_REQUEST,
                         "deadline_ms must be an integer "
                         "millisecond budget")
    if v <= 0:
        return None, err(
            ERR_DEADLINE_EXCEEDED,
            f"end-to-end deadline budget already spent ({v} ms "
            "remaining at admission) — nothing was admitted; "
            "resubmit with a fresh --deadline-s",
            deadline_ms=v)
    return v, None


def handle_logs(req: dict, log_path: str | None) -> dict:
    """The ``logs`` verb body, shared by the serve daemon and the
    fleet router (one implementation, so a limit-bound or filter-field
    change cannot land in only one of them): validate the limit,
    filter the server's own ``--log-json`` via ``obs/logquery.py``
    (rotated ``.1`` generation included), answer the newest matches
    oldest-first."""
    if not log_path:
        return err(ERR_BAD_REQUEST,
                   "this server runs without --log-json; there is "
                   "no event log to query")
    limit = req.get("limit", 1000)
    if not isinstance(limit, int) or isinstance(limit, bool) \
            or not 1 <= limit <= 10000:
        return err(ERR_BAD_REQUEST,
                   "limit must be an integer in [1, 10000]")
    from pwasm_tpu.obs.logquery import query_log
    lines = query_log(log_path,
                      trace_id=req.get("filter_trace_id"),
                      job_id=req.get("job_id"),
                      event=req.get("event"), limit=limit)
    return ok(lines=lines, path=log_path)


def ok(**fields) -> dict:
    out = {"ok": True}
    out.update(fields)
    return out


def err(code: str, detail: str = "", **fields) -> dict:
    out = {"ok": False, "error": code, "detail": detail}
    out.update(fields)
    return out


def serve_connection(conn, dispatch, peer: str | None = None,
                     max_frame_bytes: int = MAX_FRAME_BYTES) -> None:
    """The per-connection frame-serving loop shared by the serve
    daemon and the fleet router (one implementation, so a protocol-
    loop fix cannot land in only one of them): read frames until EOF,
    answer recoverable frame errors in-band, close on fatal ones, and
    turn any ``dispatch(req, peer)`` exception into a ``bad_request``
    frame — client-controlled field types must cost the CLIENT an
    error frame, never the server a dead connection thread.  Peer
    disconnects (possibly mid-result) are swallowed: their problem,
    never the server's."""
    rfile = conn.makefile("rb")
    wfile = conn.makefile("wb")
    try:
        while True:
            try:
                req = read_frame(rfile, max_frame_bytes)
            except FrameError as e:
                write_frame(wfile, err(e.code, str(e)))
                if e.fatal:
                    return
                continue
            if req is None:
                return
            try:
                resp = dispatch(req, peer=peer)
            except Exception as e:
                resp = err(ERR_BAD_REQUEST,
                           f"{type(e).__name__}: {e}")
            write_frame(wfile, resp)
    except (BrokenPipeError, ConnectionResetError, OSError,
            ValueError):
        pass
    finally:
        for f in (rfile, wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            conn.close()
        except OSError:
            pass


def write_frame(wfile, obj: dict) -> None:
    """Serialize one frame onto a buffered binary writer and flush —
    the peer blocks on the newline, so a buffered-but-unflushed frame
    is a hang, not a latency."""
    wfile.write(json.dumps(obj, separators=(",", ":")).encode("utf-8")
                + b"\n")
    wfile.flush()


def read_frame(rfile, max_bytes: int = MAX_FRAME_BYTES) -> dict | None:
    """Read and parse one frame from a buffered binary reader.

    Returns the parsed object, or ``None`` on a clean EOF (peer closed
    between frames).  Raises :class:`FrameError` for an oversized line
    (fatal — the connection must be closed), a truncated final line
    (peer died mid-frame), a line that is not JSON, or JSON that is not
    an object."""
    line = rfile.readline(max_bytes + 1)
    if not line:
        return None
    if len(line) > max_bytes:
        raise FrameError(
            ERR_FRAME_TOO_LARGE,
            f"frame exceeds {max_bytes} bytes", fatal=True)
    if not line.endswith(b"\n"):
        # EOF mid-line: the peer vanished mid-frame — nothing usable
        raise FrameError(ERR_BAD_JSON, "truncated frame at EOF",
                         fatal=True)
    try:
        obj = json.loads(line)
    except RecursionError:
        # a JSON bomb (thousands of nested containers) overflows the
        # parser's stack with RecursionError, not ValueError — found
        # by qa/protocol_fuzz.py; without this clause the bomb kills
        # the connection THREAD with a traceback instead of costing
        # the client an error frame
        raise FrameError(ERR_BAD_JSON,
                         "frame nesting exceeds the parser's depth")
    except ValueError as e:
        raise FrameError(ERR_BAD_JSON, f"unparseable frame: {e}")
    if not isinstance(obj, dict):
        raise FrameError(ERR_BAD_JSON, "frame is not a JSON object")
    return obj
