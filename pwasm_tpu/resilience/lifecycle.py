"""Graceful drain: preemption-grade process lifecycle.

On a real TPU fleet *preemption is the common failure mode*: the
scheduler SIGTERMs the pod and gives it seconds to leave.  Before this
module a SIGTERM killed the run wherever it stood — mid-batch, mid
checkpoint, buffered rows unflushed — and the operator got whatever the
batch checkpoints happened to have made durable.  :class:`SignalDrain`
turns that into a first-class, *scripted* exit:

- the FIRST ``SIGTERM``/``SIGINT`` only sets a flag.  The CLI's main
  loop checks it at every batch boundary: it stops consuming input,
  lets the in-flight batch (and the two-deep device pipeline) complete,
  flushes a final ``<report>.ckpt`` + a partial ``--stats``, and exits
  with :data:`~pwasm_tpu.core.errors.EXIT_PREEMPTED` (75, EX_TEMPFAIL)
  — the documented "preempted, resumable" status.  ``--resume``
  completes the run byte-identically to an uninterrupted one;
- a SECOND signal hard-aborts (``os._exit(128 + signum)``): the
  operator who presses Ctrl-C twice means *now*, and the batch
  checkpoints already bound the loss to the current batch;
- the scripted ``preempt=N`` fault leg (``resilience.faults``) drives
  the same flag from the supervised-call clock, so tests and chaos
  drills exercise the drain deterministically, without real signals.

Handlers are installed only on the main thread (``signal.signal``
raises elsewhere; the drain then simply never triggers via signals —
the ``preempt=`` leg still works) and always restored on exit, so
embedding callers (pytest, servers) keep their own handlers.
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from pwasm_tpu.core.errors import EXIT_PREEMPTED

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptedError(BaseException):
    """Raised by :meth:`SignalDrain.request` while an *interruptible
    phase* is armed (see :meth:`SignalDrain.interrupting`).  Derives
    from BaseException so no retry/fallback layer can swallow it — the
    phase it aborts (the end-of-run MSA/consensus tail) is rebuilt
    whole by ``--resume``, so unwinding it mid-flight loses nothing."""


class SignalDrain:
    """Flag-based drain coordinator (see module docstring).

    ``hard_exit`` is injectable for tests (defaults to ``os._exit`` —
    a hard abort must not run atexit hooks or finally blocks; that is
    the point).  Use as a context manager around the main loop::

        with SignalDrain(stderr=stderr) as drain:
            ...
            if drain.requested:
                # batch boundary: drain + checkpoint + exit 75
    """

    def __init__(self, stderr=None, hard_exit=None):
        from pwasm_tpu.obs import NULL_OBS
        self.stderr = stderr if stderr is not None else sys.stderr
        self._hard_exit = hard_exit if hard_exit is not None else os._exit
        self.obs = NULL_OBS   # rebound by cli.run / the daemon so the
        #   drain request lands in the structured event log too;
        #   EventLog.emit never raises and bounds its lock acquire
        #   (a handler interrupting the thread that holds the lock
        #   drops the line instead of deadlocking), so this is
        #   signal-handler-safe like _say below
        self.reason: str | None = None
        self._prev: dict = {}
        self._interrupt = False   # inside an interruptible phase:
        #                           request() raises PreemptedError
        self._interrupt_tid: int | None = None  # the thread that ARMED
        #   the phase: only a request() made on that same thread may
        #   raise into it.  In the one-shot CLI both are the main
        #   thread (signal handlers run there), so behavior is
        #   unchanged; in a serve daemon, the daemon thread requesting
        #   a worker-thread job's drain must only set the flag — an
        #   exception raised in the DAEMON thread would kill the
        #   service, not the job (the job still honors the flag at its
        #   next batch boundary)

    # ---- state ---------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self.reason is not None

    def request(self, reason: str) -> None:
        """Ask for a graceful drain (idempotent — the first reason
        wins).  Called by the signal handler and by the scripted
        ``preempt=N`` fault leg.  Inside an :meth:`interrupting` phase
        this RAISES :class:`PreemptedError` (into whatever the main
        thread is executing) instead of waiting for a batch boundary
        the phase will never reach."""
        if self.reason is None:
            self.reason = reason   # the flag FIRST: the drain must
            #                        survive a failed message below
            self.obs.event("drain", reason=reason)
            self._say(f"pwasm: {reason} — draining: finishing the "
                      "in-flight batch, flushing a final checkpoint, "
                      f"then exiting resumable (exit {EXIT_PREEMPTED})"
                      "; a second signal hard-aborts")
        if self._interrupt \
                and threading.get_ident() == self._interrupt_tid:
            raise PreemptedError(self.reason)

    def _say(self, msg: str) -> None:
        """Best-effort stderr line, SAFE FROM A SIGNAL HANDLER: a
        buffered ``print`` re-entered while the main thread is mid-write
        to the same stream raises RuntimeError (reentrant call) — which
        would propagate into the main thread at an arbitrary bytecode
        and kill the run the drain exists to save.  On any failure fall
        back to the unbuffered fd (if there is one), else drop the
        message; the drain flag is already set either way."""
        try:
            print(msg, file=self.stderr)
        except Exception:
            try:
                os.write(2, msg.encode("utf-8", "replace") + b"\n")
            except OSError:
                pass

    def interrupting(self):
        """Context manager arming the *interruptible phase*: while
        active, a drain request aborts the phase immediately by raising
        :class:`PreemptedError` (and one already pending raises on
        entry).  Used around the end-of-run MSA/consensus tail — past
        the batch loop there is no next batch boundary to drain at,
        the report + checkpoint are already durable, and ``--resume``
        rebuilds the whole tail from scratch, so aborting it mid-model
        loses nothing while finishing it could outlive a preemption
        grace period."""
        return _Interrupting(self)

    # ---- signal plumbing -----------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self.requested:
            # second signal: the operator means NOW.  os._exit skips
            # every finally/atexit — exactly SIGKILL-shaped, and the
            # batch checkpoints already bound the loss.
            self._say(f"pwasm: second signal ({name}) — hard abort")
            self._hard_exit(128 + signum)
            return
        self.request(f"caught {name}")

    def install(self) -> "SignalDrain":
        for sig in _SIGNALS:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                # not the main thread: signals cannot be installed —
                # the drain still works via the preempt= fault leg
                pass
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()

    def __enter__(self) -> "SignalDrain":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class _Interrupting:
    def __init__(self, drain: SignalDrain):
        self._drain = drain

    def __enter__(self):
        self._drain._interrupt = True
        self._drain._interrupt_tid = threading.get_ident()
        if self._drain.requested:
            # the drain landed between the batch loop's last check and
            # this phase starting: honor it before any tail work
            raise PreemptedError(self._drain.reason)
        return self._drain

    def __exit__(self, *exc) -> None:
        self._drain._interrupt = False
        self._drain._interrupt_tid = None
