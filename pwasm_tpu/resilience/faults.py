"""Deterministic fault injection for the device pipeline.

Every supervised device call site (``resilience.supervisor``) consults
the armed :class:`FaultPlan` before and after the real work.  Draws are
seeded and keyed on ``(seed, site, per-site attempt counter)``, so a
given spec produces the identical fault sequence on every run — the
property the byte-parity acceptance test rests on — and retries of the
same batch advance the counter, so a fault-free draw eventually lets
the batch through.

Spec format (``--inject-faults=SPEC`` / ``PWASM_INJECT_FAULTS``), a
comma-separated ``key=value`` list:

  ``seed=N``      RNG seed (default 0)
  ``rate=P``      per-attempt fault probability in [0, 1] (default 0)
  ``kinds=a+b``   fault mix, ``+``-separated from {raise, hang, nan,
                  corrupt} (default all four), drawn uniformly
  ``sites=x+y``   restrict injection to these site names (default all;
                  site names: ``ctx_scan``, ``realign``, ``consensus``,
                  ``many2many``, ``refine``)
  ``hang_s=S``    simulated hang duration in seconds (default 30;
                  meant to exceed ``--device-deadline``).  NB the
                  supervisor caps the slept time at a small multiple of
                  the armed deadline (or ~1 s when no deadline is set),
                  so an injected hang proves the timeout machinery
                  without stalling a fast test suite — see
                  :meth:`FaultPlan.effective_hang`
  ``kill=K``      raise an uncatchable :class:`InjectedKill` on the
                  K-th supervised attempt (counted across all sites,
                  and a batch skipped by an open global breaker counts
                  as one attempt) — simulates a mid-run process kill
                  for checkpoint / resume testing
  ``preempt=N``   request a GRACEFUL DRAIN (``resilience.lifecycle``)
                  at the N-th supervised call: the run finishes its
                  in-flight batch, flushes a final checkpoint and a
                  partial ``--stats``, and exits with the documented
                  preempted-resumable code (75) — the scripted twin of
                  a fleet scheduler's SIGTERM, deterministic for
                  drain/resume parity tests
  ``oom=N``       simulated device memory ceiling, in batch items: any
                  supervised attempt over a batch LARGER than N items
                  raises a ``RESOURCE_EXHAUSTED``-shaped
                  :class:`InjectedOOM` — deterministic by size, so the
                  supervisor's batch bisection provably converges (the
                  halves at or under N succeed)
  ``down=A-B``    scripted OUTAGE WINDOWS, ``+``-separated inclusive
                  1-based ranges over the global supervised-CALL
                  counter (one tick per ``BatchSupervisor.run``
                  invocation, degraded calls included): every device
                  attempt made while the counter is inside a window
                  fails with a tunnel-shaped :class:`InjectedOutage`,
                  and backend probes report unreachable — so tests can
                  script "device dies at batch A, returns after batch
                  B" and assert the breaker opens AND recloses

Example: ``--inject-faults=seed=7,rate=0.3,kinds=raise+nan+corrupt``;
a flap: ``--inject-faults=down=2-4``.

Fault kinds:

- ``raise``    the device call raises :class:`InjectedFault`;
- ``hang``     the call sleeps ``hang_s`` seconds first (a supervisor
               deadline turns that into ``DeadlineExceeded``);
- ``nan``      float outputs get NaNs written into a seeded slice
               (integer outputs get out-of-range garbage instead);
- ``corrupt``  one output array gets a seeded slice overwritten with
               out-of-domain values — the silent-corruption case the
               guardrails must catch.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass, field

import numpy as np

KINDS = ("raise", "hang", "nan", "corrupt")

# garbage written by corrupt/nan into integer arrays: far outside every
# guarded domain (codes, flags, positions, scores) but well inside
# int32, so the corruption is silent at the dtype level
_INT_GARBAGE = 0x3FFFFFF0


class InjectedFault(RuntimeError):
    """The exception a ``raise`` fault throws inside a device call."""


class InjectedOutage(InjectedFault):
    """The tunnel-shaped error a scripted ``down=A-B`` outage window
    throws for every device attempt inside the window — distinct from
    :class:`InjectedFault` so tests can tell a scripted backend outage
    from a random computational fault."""


class InjectedOOM(InjectedFault):
    """The ``RESOURCE_EXHAUSTED``-shaped error the ``oom=N`` leg throws
    for any supervised attempt whose batch exceeds the simulated memory
    ceiling.  The message deliberately carries the real XLA marker so
    the supervisor's OOM *classifier* (not an isinstance check) is what
    the injection exercises — the same code path a live chip's
    allocation failure takes."""


class InjectedKill(BaseException):
    """Simulated process kill (``kill=K``).  Derives from BaseException
    so no retry/fallback layer can swallow it — it unwinds the whole
    run exactly like SIGKILL would end it, leaving only what the
    batch checkpoints made durable.  Inside a warm ``serve`` process
    the blast radius is the JOB, not the daemon: the worker catches it
    at the job boundary and marks the job failed (its checkpointed
    prefix stays resumable), because a scripted kill must never take
    out the other tenants of a shared process."""


@dataclass
class FaultPlan:
    seed: int = 0
    rate: float = 0.0
    kinds: tuple[str, ...] = KINDS
    sites: frozenset[str] | None = None   # None = all sites
    hang_s: float = 30.0
    kill: int = 0                         # 0 = disabled; else 1-based
    preempt: int = 0                      # 0 = disabled; else 1-based
    #          supervised call at which a graceful drain is requested
    oom: int = 0                          # 0 = disabled; else the
    #          simulated device memory ceiling in batch items
    down: tuple[tuple[int, int], ...] = ()  # outage windows over _calls
    on_preempt: object = field(default=None, repr=False)  # drain hook:
    #          (reason: str) -> None, wired to SignalDrain.request by
    #          the CLI so preempt= drives the same flag a SIGTERM sets
    _site_counters: dict = field(default_factory=dict, repr=False)
    _attempts: int = field(default=0, repr=False)
    _calls: int = field(default=0, repr=False)  # supervised-call clock
    #          (one tick per BatchSupervisor.run invocation, degraded
    #          calls included) — the down= windows are scripted on it,
    #          and it is persisted in <report>.ckpt so a --resume lands
    #          back inside the same scripted window
    _preempted: bool = field(default=False, repr=False)

    def note_call(self) -> None:
        """Advance the supervised-call clock — called once at every
        ``BatchSupervisor.run`` entry, whether or not the device is
        attempted (an open breaker must not freeze a scripted outage
        window, or a flap could never end)."""
        self._calls += 1
        if self.preempt and not self._preempted \
                and self._calls >= self.preempt:
            # fires once; >= (not ==) so a --resume whose restored
            # clock already passed the mark still drains rather than
            # silently disarming the scripted preemption
            self._preempted = True
            if self.on_preempt is not None:
                self.on_preempt(f"injected preemption at supervised "
                                f"call {self._calls}")

    def oom_for(self, size: int | None) -> bool:
        """True when an attempt over ``size`` batch items must raise
        :class:`InjectedOOM` (deterministic by size — retrying the same
        shape can never succeed, which is the scenario the supervisor's
        bisection exists for)."""
        return bool(self.oom) and size is not None and size > self.oom

    def note_skipped(self, site: str) -> None:
        """A supervised call skipped by an open breaker still counts as
        one attempt toward ``kill=K`` — a kill scripted to land
        mid-outage must fire even though no device draw happens."""
        self._attempts += 1
        if self.kill and self._attempts >= self.kill:
            raise InjectedKill(
                f"injected kill at supervised attempt {self._attempts} "
                f"(site {site}, breaker open)")

    def in_outage(self) -> bool:
        """True while the supervised-call clock is inside a ``down=``
        window."""
        return any(a <= self._calls <= b for a, b in self.down)

    def outage_probe(self) -> str | None:
        """The scripted answer a backend probe must give: a diagnostic
        while inside an outage window, None outside (fall through to
        the real probe)."""
        if self.in_outage():
            return (f"injected outage (down window, supervised call "
                    f"{self._calls})")
        return None

    def effective_hang(self, deadline_s: float | None) -> float:
        """The capped sleep a ``hang`` fault actually performs: hangs
        exist to prove the deadline machinery, so sleeping much past
        the deadline (or for the full default 30 s when NO deadline is
        armed) only stalls the suite without proving anything more —
        cap at 4x the deadline, or ~1 s deadline-less."""
        cap = 4.0 * deadline_s if deadline_s else 1.0
        return min(self.hang_s, cap)

    def draw(self, site: str) -> str | None:
        """One deterministic fault draw for an attempt at ``site``.
        Returns a kind from :data:`KINDS` (or ``"down"`` inside a
        scripted outage window) or None, advancing the per-site counter
        either way.  Raises :class:`InjectedKill` when the global
        attempt counter reaches ``kill``."""
        self._attempts += 1
        if self.kill and self._attempts >= self.kill:
            raise InjectedKill(
                f"injected kill at supervised attempt {self._attempts} "
                f"(site {site})")
        k = self._site_counters.get(site, 0)
        self._site_counters[site] = k + 1
        if self.in_outage():
            # a dead tunnel fails every site, whatever sites= says —
            # and deterministically, whatever rate= says
            return "down"
        if self.sites is not None and site not in self.sites:
            return None
        rng = random.Random(f"{self.seed}|{site}|{k}")
        if rng.random() >= self.rate:
            return None
        return self.kinds[rng.randrange(len(self.kinds))]

    def corrupt(self, obj, site: str, kind: str):
        """Deterministically corrupt one numpy array inside ``obj``
        (dicts/tuples/lists walked recursively; everything else passes
        through untouched).  Returns a modified deep-ish copy — the
        original arrays are never written, so a retry that reuses a
        cached device result is not poisoned."""
        leaves: list[tuple] = []
        obj = _walk_copy(obj, leaves)
        if not leaves:
            return obj
        k = self._site_counters.get(site, 0)
        rng = random.Random(f"{self.seed}|corrupt|{site}|{k}")
        _, arr = leaves[rng.randrange(len(leaves))]
        flat = arr.reshape(-1)
        # corrupt a PREFIX slice: device batches are padded to compile
        # buckets, so a random offset would usually land in padding no
        # consumer ever reads — corruption that cannot be consequential
        # proves nothing about the guardrails
        n = max(1, flat.shape[0] // 8)
        start = 0
        if kind == "nan" and np.issubdtype(arr.dtype, np.floating):
            flat[start:start + n] = np.nan
        else:
            info = np.iinfo(arr.dtype) if np.issubdtype(
                arr.dtype, np.integer) else None
            val = _INT_GARBAGE if info is None or info.max >= _INT_GARBAGE \
                else info.max
            flat[start:start + n] = val
        return obj


def _walk_copy(obj, leaves: list):
    """Copy containers and ndarray leaves, collecting (path, array)
    pairs for the corruptible leaves (non-empty numeric/bool arrays)."""
    if isinstance(obj, dict):
        return {k: _walk_copy(v, leaves) for k, v in obj.items()}
    if isinstance(obj, (tuple, list)):
        out = [_walk_copy(v, leaves) for v in obj]
        return tuple(out) if isinstance(obj, tuple) else out
    if isinstance(obj, np.ndarray) and obj.size \
            and obj.dtype.kind in "iuf":
        # bool arrays are NOT corruption targets: a flipped flag is a
        # legal value no domain invariant can reject — the modeled
        # fault is out-of-domain garbage (bad DMA / stuck lanes), which
        # the guardrails are built to catch
        c = obj.copy()
        leaves.append((None, c))
        return c
    return obj


def parse_fault_spec(spec: str) -> FaultPlan:
    """Parse an ``--inject-faults`` spec string (see module docstring).
    Raises ValueError on malformed input."""
    plan = FaultPlan()
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"fault spec item without '=': {item!r}")
        key, val = item.split("=", 1)
        key = key.strip()
        val = val.strip()
        try:
            if key == "seed":
                plan.seed = int(val)
            elif key == "rate":
                plan.rate = float(val)
                if not 0.0 <= plan.rate <= 1.0:
                    raise ValueError
            elif key == "kinds":
                kinds = tuple(k for k in val.split("+") if k)
                bad = [k for k in kinds if k not in KINDS]
                if bad or not kinds:
                    raise ValueError
                plan.kinds = kinds
            elif key == "sites":
                plan.sites = frozenset(s for s in val.split("+") if s)
            elif key == "hang_s":
                plan.hang_s = float(val)
                if plan.hang_s < 0:
                    raise ValueError
            elif key == "kill":
                plan.kill = int(val)
                if plan.kill < 0:
                    raise ValueError
            elif key == "preempt":
                plan.preempt = int(val)
                if plan.preempt < 0:
                    raise ValueError
            elif key == "oom":
                plan.oom = int(val)
                if plan.oom < 0:
                    raise ValueError
            elif key == "down":
                wins = []
                for rng_s in val.split("+"):
                    a_s, _, b_s = rng_s.partition("-")
                    a, b = int(a_s), int(b_s)
                    if a < 1 or b < a:
                        raise ValueError
                    wins.append((a, b))
                if not wins:
                    raise ValueError
                plan.down = tuple(wins)
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad fault spec item: {item!r} "
                f"(keys: seed rate kinds sites hang_s kill preempt "
                f"oom down)")
    return plan


def plan_from_env() -> FaultPlan | None:
    """The env-armed plan (``PWASM_INJECT_FAULTS``), for subprocesses
    that never see the CLI flag; None when unset/empty.  A malformed
    env spec raises — a debug knob that silently disarms would be worse
    than a crash."""
    spec = os.environ.get("PWASM_INJECT_FAULTS", "")
    return parse_fault_spec(spec) if spec else None
