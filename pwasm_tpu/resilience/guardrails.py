"""Cheap invariant validation of device outputs.

A device that silently returns garbage is worse than one that raises:
the garbage lands in the report and the run "succeeds".  Each check
below costs O(batch) numpy work — noise next to the device program it
guards — and raises :class:`GuardrailViolation`, which the supervisor
treats exactly like a device exception: the batch is re-executed, and
only validated output is ever formatted.

The checks are *domain* invariants, not recomputation: value ranges of
the int8/ASCII code spaces, index bounds against the reference length,
and the conservation laws the kernels guarantee by construction
(pileup counts sum to column coverage; a re-alignment walk consumes
exactly ``t_len`` target bases).  A corruption that passes all of them
is allowed to differ from the host path only where the host path could
have produced it too.
"""

from __future__ import annotations

import numpy as np


class GuardrailViolation(Exception):
    """A device output failed invariant validation (treated as a device
    fault: retried, then degraded, never written to the report)."""


def _fail(site: str, msg: str):
    raise GuardrailViolation(f"{site}: {msg}")


def check_array(arr, name: str, *, site: str, shape=None, dtype_kind=None,
                lo=None, hi=None, finite: bool = True) -> None:
    """Shape/dtype/range/finiteness check for one output tensor."""
    a = np.asarray(arr)
    if shape is not None and tuple(a.shape) != tuple(shape):
        _fail(site, f"{name} shape {a.shape} != expected {tuple(shape)}")
    if dtype_kind is not None and a.dtype.kind not in dtype_kind:
        _fail(site, f"{name} dtype {a.dtype} not of kind {dtype_kind!r}")
    if a.size == 0:
        return
    if finite and a.dtype.kind == "f" and not np.isfinite(a).all():
        _fail(site, f"{name} contains non-finite values")
    if lo is not None and int(a.min()) < lo:
        _fail(site, f"{name} min {a.min()} < {lo}")
    if hi is not None and int(a.max()) > hi:
        _fail(site, f"{name} max {a.max()} > {hi}")


def check_ctx_scan(host: dict, n_events: int, ref_len: int,
                   n_motifs: int, skip_codan: bool,
                   site: str = "ctx_scan") -> None:
    """Validate a fetched ctx_scan output dict (device_report's host
    fetch): leading dims match the event batch, flag/code/position
    tensors stay inside their domains.  AA codes are ASCII (0 when
    unset), positions are bounded by the reference's codon count."""
    aa_hi = 127
    # AA positions are 1-based codon indices; the frameshift stop scan
    # may run a few codons past the reference end (the modified suffix
    # includes up to MAX_EV inserted bases), so the bound is loose by a
    # small constant — injected garbage sits orders of magnitude above
    pos_hi = ref_len + 64
    req = ("aa", "aapos", "hpoly", "motif")
    for k in req:
        if k not in host:
            _fail(site, f"missing output {k!r}")
    # pack_events pads the event batch to a compile bucket, so every
    # leading dim is >= the live event count (and all equal); only the
    # live prefix reaches the report, so ranges are checked on it alone
    lead = None
    for k, v in host.items():
        a = np.asarray(v)
        if a.ndim == 0 or a.shape[0] < n_events:
            _fail(site, f"{k} leading dim {a.shape} < batch {n_events}")
        if lead is None:
            lead = a.shape[0]
        elif a.shape[0] != lead:
            _fail(site, f"{k} leading dim {a.shape[0]} != {lead}")
        if a.dtype.kind == "f" and not np.isfinite(a[:n_events]).all():
            _fail(site, f"{k} contains non-finite values")

    def live(k):
        return np.asarray(host[k])[:n_events]

    check_array(live("aa"), "aa", site=site, lo=0, hi=aa_hi)
    check_array(live("aapos"), "aapos", site=site, lo=-1, hi=pos_hi)
    check_array(live("hpoly"), "hpoly", site=site, lo=0, hi=1)
    check_array(live("motif"), "motif", site=site, lo=0, hi=n_motifs)
    if not skip_codan:
        for k in ("s_orig_aa", "s_new_aa", "aa4", "maa4"):
            if k in host:
                check_array(live(k), k, site=site, lo=0, hi=aa_hi)
        for k in ("s_valid", "aa4_valid", "maa4_valid", "s_mismatch"):
            if k in host:
                check_array(live(k), k, site=site, lo=0, hi=1)
        if "s_aapos" in host:
            check_array(live("s_aapos"), "s_aapos", site=site, lo=-1,
                        hi=pos_hi)
        if "stop_aapos" in host:
            check_array(live("stop_aapos"), "stop_aapos", site=site,
                        lo=-1, hi=pos_hi)


def check_realign(scores, leads, iy_runs, ops_rows, ok, q_lens, t_lens,
                  match_score: int, site: str = "realign") -> None:
    """Validate one realign dispatch (``banded_realign_rows`` outputs).

    Domain checks on every lane plus the conservation law on ``ok``
    lanes: the walk's forward op string consumes exactly ``t_len``
    target bases, i.e. ``lead + sum(iy_runs) + #DIAG rows == t_len``
    (query bases are consumed structurally — one op per live row).
    Scores are bounded above by a perfect match of the whole query."""
    from pwasm_tpu.ops.realign import OP_DIAG, OP_IX

    scores = np.asarray(scores)
    leads = np.asarray(leads)
    iy = np.asarray(iy_runs)
    ops = np.asarray(ops_rows)
    okv = np.asarray(ok)
    q_lens = np.asarray(q_lens)
    t_lens = np.asarray(t_lens)
    T = q_lens.shape[0]
    m_max = iy.shape[1] if iy.ndim == 2 else 0
    check_array(scores, "scores", site=site, shape=(T,))
    check_array(leads, "leads", site=site, shape=(T,), lo=0)
    check_array(iy, "iy_runs", site=site, shape=(T, m_max), lo=0)
    check_array(ops, "ops_rows", site=site, shape=(T, m_max), lo=0,
                hi=max(OP_DIAG, OP_IX))
    check_array(okv, "ok", site=site, shape=(T,), dtype_kind="b")
    if not okv.any():
        return
    live = np.arange(m_max)[None, :] < q_lens[:, None]
    diag = ((ops == OP_DIAG) & live).sum(axis=1)
    consumed = leads + np.where(live, iy, 0).sum(axis=1) + diag
    bad = okv & (consumed != t_lens)
    if bad.any():
        k = int(np.argmax(bad))
        _fail(site, f"lane {k}: walk consumes {consumed[k]} target "
                    f"bases != t_len {t_lens[k]}")
    hi = q_lens * match_score
    if (okv & (scores > hi)).any():
        _fail(site, "score exceeds the perfect-match bound")


def check_consensus(chars, counts, pile, site: str = "consensus") -> None:
    """Validate a device consensus (``device_counts_votes`` output)
    against the pileup it was computed from: per-column class counts
    must sum to the column's coverage (entries with codes 0..5 — the
    pileup-count conservation law), and vote characters must come from
    the consensus alphabet (0 = zero coverage)."""
    chars = np.asarray(chars)
    counts = np.asarray(counts)
    pile = np.asarray(pile)
    ncols = pile.shape[1]
    check_array(counts, "counts", site=site, shape=(ncols, 6), lo=0)
    check_array(chars, "chars", site=site, shape=(ncols,))
    alphabet = {0} | set(b"ACGTN-*")
    vals = set(np.unique(chars).tolist())
    if not vals <= alphabet:
        _fail(site, f"vote characters outside the consensus alphabet: "
                    f"{sorted(vals - alphabet)[:5]}")
    coverage = (pile < 6).sum(axis=0, dtype=np.int64)
    got = counts.sum(axis=1, dtype=np.int64)
    if (got != coverage).any():
        k = int(np.argmax(got != coverage))
        _fail(site, f"column {k}: counts sum {got[k]} != coverage "
                    f"{coverage[k]} (pileup-count conservation)")


def check_refine_clips(clipL, clipR, seqlens, site: str = "refine") -> None:
    """Validate a device clip-refinement result: per-member clip counts
    are non-negative and bounded by the member's sequence length (a
    clip can never exceed the sequence it trims)."""
    clipL = np.asarray(clipL)
    clipR = np.asarray(clipR)
    seqlens = np.asarray(seqlens)
    M = seqlens.shape[0]
    check_array(clipL, "clipL", site=site, shape=(M,), lo=0)
    check_array(clipR, "clipR", site=site, shape=(M,), lo=0)
    if (clipL > seqlens).any() or (clipR > seqlens).any():
        _fail(site, "clip exceeds the member sequence length")


def check_scores_matrix(scores, n_rows: int, n_cols: int,
                        max_per_base: int, m: int,
                        site: str = "many2many") -> None:
    """Validate a (Q, T) banded-DP score matrix: shape, integer dtype,
    and the perfect-match upper bound ``m * match`` (NEG sentinels are
    legal below)."""
    s = np.asarray(scores)
    check_array(s, "scores", site=site, shape=(n_rows, n_cols),
                dtype_kind="iu")
    if s.size and int(s.max()) > m * max_per_base:
        _fail(site, f"score {s.max()} exceeds the perfect-match bound "
                    f"{m * max_per_base}")
