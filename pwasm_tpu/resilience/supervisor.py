"""Supervised execution of device batches.

The CLI's device pipeline (ctx_scan report batches, --realign DP
dispatches, the MSA consensus launch, the many2many scorer) routes
every device round-trip through :meth:`BatchSupervisor.run`, which
adds the failure handling a long batch run needs and the reference's
fail-fast model lacks (SURVEY.md §2.5.12 vs §5):

- bounded **retry** with exponential backoff + jitter — transient
  device faults re-execute instead of killing the run;
- a per-attempt **deadline** (``--device-deadline``) — a hung tunnel
  costs one timeout, not an indefinite stall (the attempt runs in a
  worker thread that is abandoned on timeout, the only portable way to
  walk away from a hung XLA call);
- **guardrail validation** — out-of-domain output counts as a fault
  and is re-executed, never formatted;
- a **circuit breaker** with PER-SITE failure windows — after N
  *consecutive* failures at one site (ctx_scan / realign / consensus /
  refine / many2many; thresholds overridable per site via
  ``ResiliencePolicy.site_thresholds``) the device is suspected
  unhealthy and one bounded ``probe_backend`` check supplies the
  diagnostic.  An unreachable probe opens the breaker **globally** (a
  dead backend fails every site) and every later call degrades
  straight to its host fallback without touching the device again.  A
  healthy probe half-opens that site instead: the failures were
  computational, not a dead backend, so device attempts continue — but
  a site that keeps exhausting its window (``site_trip_limit``
  half-opens) trips its OWN breaker: a persistently-miscompiling
  program must stop burning retries at that site while the other sites
  keep their device path;
- the degradation **policy**: ``--fallback=cpu`` (default) runs the
  bit-exact host path, ``--fallback=fail`` aborts the run loudly with
  a :class:`ResilienceError` — for pipelines where silent CPU walls
  are worse than a dead job;
- **OOM-aware bisection**: a device allocation failure
  (``RESOURCE_EXHAUSTED`` / XLA OOM, classified by
  :func:`is_oom_error`) is a different animal from every fault above —
  retrying the identical shape re-fails deterministically, and the
  backend is healthy, so charging the breaker (or degrading to the
  host) would be wrong while a smaller batch can succeed.  A site that
  declares a :class:`BisectableBatch` gets its batch split in half
  recursively (each half re-supervised in full) down to a floor, the
  run's pow2 batch ceiling is demoted (``bucket_ceiling``, persisted
  in the checkpoint) so future flushes pre-chunk instead of re-OOMing,
  and ``oom_events``/``batch_splits``/``bucket_demotions`` land in the
  stats — the host fallback is reached only when floor-size splits
  still OOM;
- **recovery** (``resilience.health``): an open global breaker is no
  longer terminal — a :class:`BackendHealthMonitor` re-probes the
  backend on a capped-exponential schedule and, after its hysteresis
  of consecutive healthy probes, the breaker RECLOSES: subsequent
  batches route back to the device (mid-run CPU->device re-promotion)
  and the per-site trip state resets, because the failures that opened
  the breaker belonged to the outage, not the sites.  Breaker and
  fault-plan state are exportable (:meth:`BatchSupervisor.export_state`)
  into the ``<report>.ckpt`` so a ``--resume`` after a kill inherits
  them.

Every decision increments a counter on the shared ``RunStats`` and
surfaces in the ``--stats`` JSON ``resilience`` block.
"""

from __future__ import annotations

import random
import sys
import threading
import time
from dataclasses import dataclass, replace

from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.obs import NULL_OBS
from pwasm_tpu.resilience.faults import FaultPlan
from pwasm_tpu.resilience.guardrails import GuardrailViolation

# substrings that mark a device ALLOCATION failure, lower-cased: the
# XLA status name every jax backend surfaces on OOM, plus the two
# free-text forms seen from the TPU allocator and the BFC allocator.
# Classification is textual on purpose — jaxlib's exception classes
# moved across releases (jaxcompat shields us elsewhere), and the
# injected InjectedOOM carries the same marker so the fault leg proves
# the LIVE classifier, not a parallel isinstance path.
_OOM_MARKERS = ("resource_exhausted", "out of memory",
                "failed to allocate")


def is_oom_error(e: BaseException | None) -> bool:
    """True when ``e`` is a device allocation failure — the failure
    class where retrying the identical shape is pointless (the
    allocation will fail again) and the breaker must stay untouched
    (the backend is healthy, the *batch* is too big): the supervisor
    bisects instead."""
    if e is None:
        return False
    s = f"{type(e).__name__}: {e}".lower()
    return any(m in s for m in _OOM_MARKERS)


class DeadlineExceeded(Exception):
    """A supervised attempt outlived the per-batch deadline."""


class DeviceWorkFailed(Exception):
    """Retries exhausted (or breaker open) and the caller owns the
    degradation — raised only under ``fallback=cpu`` when ``run`` was
    given no fallback callable.  Carries the last underlying error as
    ``__cause__``."""


class ResilienceError(PwasmError):
    """Fatal under ``--fallback=fail``: device work failed after the
    bounded retries and the policy forbids degrading to the host."""


@dataclass
class BisectableBatch:
    """How a supervised site lets the supervisor SPLIT its batch when
    the device reports ``RESOURCE_EXHAUSTED``: the ordered item list
    the attempt covers, a factory building a fresh attempt over any
    sub-list, a per-part validator, and the combiner that reassembles
    the per-part results in item order.  Bisection recurses through
    ``BatchSupervisor.run`` itself, so every sub-attempt keeps the full
    supervision contract (retries, deadline, guardrails, injection) —
    only the shape shrinks."""

    items: list                 # the batch, in result order
    attempt_for: object         # (items) -> result (launch + fetch)
    combine: object             # (list[(items, result)]) -> result
    validate_for: object = None  # (result, items) -> None, may raise
    #                              GuardrailViolation
    floor: int = 1              # never split below this many items


@dataclass
class ResiliencePolicy:
    max_retries: int = 2          # extra attempts after the first
    backoff_s: float = 0.05       # first retry delay
    backoff_cap_s: float = 2.0    # ceiling for the exponential delay
    jitter: float = 0.5           # +[0, jitter) fraction of the delay
    deadline_s: float | None = None  # per-attempt wall ceiling
    fallback: str = "cpu"         # cpu = degrade to host; fail = abort
    breaker_threshold: int = 5    # consecutive failures (per site) to
    #                               suspect the backend and probe it
    site_thresholds: dict | None = None  # per-site overrides of
    #                               breaker_threshold, e.g.
    #                               {"ctx_scan": 3, "realign": 8}
    site_trip_limit: int = 3      # healthy-probe half-opens before a
    #                               site's OWN breaker trips (the
    #                               persistently-failing-program case)
    repromote_after: int = 8      # consecutive clean sized flushes at
    #                               a demoted bucket_ceiling before it
    #                               probation-raises one pow2 step —
    #                               so a long run (or a long-lived
    #                               serve process) that OOMed once
    #                               does not stay chunked forever.
    #                               0 disables re-promotion.

    def threshold_for(self, site: str) -> int:
        if self.site_thresholds:
            return int(self.site_thresholds.get(
                site, self.breaker_threshold))
        return self.breaker_threshold


class BatchSupervisor:
    """One per run, shared by every supervised site.  Failure windows
    are PER SITE (a guardrail storm at ctx_scan must not charge the
    realign site's breaker); the probe-confirmed-dead-backend breaker
    stays global on purpose: a dead backend fails every site.

    ``stats`` is the run's ``RunStats`` (resilience counters optional —
    missing attributes are ignored so the class also works bare).
    ``faults`` arms deterministic fault injection (``FaultPlan``).
    ``probe`` overrides the breaker's backend health check (tests).
    ``monitor`` is a ``resilience.health.BackendHealthMonitor`` — when
    given, an open global breaker is re-probed and can RECLOSE
    (mid-run device re-promotion); without one the breaker stays
    terminal (``--recover=off``)."""

    def __init__(self, policy: ResiliencePolicy | None = None,
                 stats=None, stderr=None, faults: FaultPlan | None = None,
                 probe=None, monitor=None, obs=None):
        self.policy = policy or ResiliencePolicy()
        self.stats = stats
        self.stderr = stderr if stderr is not None else sys.stderr
        self.obs = obs if obs is not None else NULL_OBS
        self.faults = faults
        self._probe = probe
        self.monitor = monitor
        if monitor is not None and monitor.probe is None:
            # the monitor re-probes through the same (bounded,
            # fault-plan-aware) check the breaker trips on
            monitor.probe = self._probe_backend
        self._consecutive: dict[str, int] = {}  # site -> failure window
        self._half_opens: dict[str, int] = {}   # site -> healthy-probe
        #                                         half-open count
        self._site_open: set[str] = set()       # per-site open breakers
        self.breaker_open = False               # global (backend dead)
        self.recloses = 0                       # global breaker recloses
        self._degraded_t0: float | None = None  # breaker-open wall start
        self.bucket_ceiling: int | None = None  # pow2 batch-size
        #          ceiling demoted by a device OOM: call sites that
        #          declare a BisectableBatch pre-chunk their batches to
        #          it for the rest of the run (and it persists in the
        #          <report>.ckpt), so one RESOURCE_EXHAUSTED costs one
        #          bisection, not one per future flush
        self._ceiling_clean = 0                 # consecutive clean
        #          sized flushes since the last OOM/re-promotion —
        #          the probation counter behind repromote_after
        self._ceiling_origin: int | None = None  # the largest pow2
        #          bucket an OOM demoted FROM: re-promotion that
        #          climbs back to it RESTORES the ceiling to None
        #          (undemoted) instead of doubling past what ever
        #          failed — the up-transition terminates
        self._in_bisect = 0                     # bisection recursion
        #          depth: halves run right after an OOM and must not
        #          count toward the ceiling's probation
        # jitter exists to de-synchronize retry storms across the many
        # processes of a batch fleet, so it must be seeded per process
        # (a fixed seed would make every process retry at the same
        # instants — the exact storm jitter is meant to break).  It
        # only perturbs sleep times, never results.
        import os
        self._rng = random.Random(os.getpid() ^ int(time.time() * 1e3))

    # ---- counters ------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        if self.stats is not None and hasattr(self.stats, name):
            setattr(self.stats, name, getattr(self.stats, name) + n)

    def _warn(self, msg: str) -> None:
        print(f"pwasm: {msg}", file=self.stderr)

    # ---- the supervised call -------------------------------------------
    def run(self, site: str, attempt, validate=None, fallback=None,
            bisect: BisectableBatch | None = None,
            size: int | None = None):
        """Execute ``attempt()`` under the policy and return its
        (validated) result.

        ``validate(result)`` raises ``GuardrailViolation`` to reject
        output; rejection counts as a device fault and re-executes.
        ``fallback()`` is the bit-exact host path used when the device
        is given up on (``fallback=cpu`` policy); without one, gives up
        by raising :class:`DeviceWorkFailed` so the caller can degrade.
        Under ``--fallback=fail`` exhaustion raises
        :class:`ResilienceError` instead (fatal).

        ``bisect`` (a :class:`BisectableBatch`) makes the attempt
        OOM-recoverable: an allocation failure (``is_oom_error`` — real
        ``RESOURCE_EXHAUSTED``/XLA OOM or the injected ``oom=`` leg) is
        NOT retried at the same shape and NEVER charges the breaker
        window; the batch is bisected recursively (down to
        ``bisect.floor``) and the pow2 batch ceiling is demoted for the
        rest of the run.  Degradation to the fallback happens only when
        even floor-size splits fail.  ``size`` declares the attempt's
        batch item count for the ``oom=`` injection (defaults to
        ``len(bisect.items)`` when a bisect spec is given)."""
        if size is None and bisect is not None:
            size = len(bisect.items)
        if self.faults is not None:
            # the scripted-outage clock ticks once per supervised call,
            # INCLUDING degraded ones — an open breaker must not freeze
            # a down= window, or a scripted flap could never end
            self.faults.note_call()
        if self.breaker_open:
            if self.monitor is not None and self.monitor.poll():
                self._reclose()
            else:
                self._count("res_degraded_batches")
                if self.faults is not None:
                    self.faults.note_skipped(site)  # may InjectedKill
                return self._degrade(site, fallback,
                                     "circuit breaker open", None)
        if site in self._site_open:
            return self._degrade(site, fallback,
                                 f"site breaker open ({site})", None)
        delay = self.policy.backoff_s
        last: BaseException | None = None
        for k in range(self.policy.max_retries + 1):
            if k:
                self._count("res_retries")
                time.sleep(min(delay * (1 + self.policy.jitter
                                        * self._rng.random()),
                               self.policy.backoff_cap_s))
                delay *= 2
            # every attempt — clean, rejected, timed out, OOMed —
            # lands exactly ONE wall observation on the per-site
            # histogram, taken at the attempt's own boundary (NOT in a
            # finally: the OOM path re-enters run() for each bisected
            # half before unwinding, and a finally would fold the
            # whole recovery into the parent attempt's sample)
            t_att = time.perf_counter()

            def _attempt_wall(_t0=t_att) -> None:
                wall = time.perf_counter() - _t0
                self.obs.observe("batch_attempt_seconds", wall,
                                 site=site)
                if self.stats is not None \
                        and hasattr(self.stats, "note_attempt_wall"):
                    # compile-vs-steady accounting (ISSUE 11): a
                    # site's first attempt is compile-inclusive
                    self.stats.note_attempt_wall(site, wall)

            try:
                if self.stats is not None \
                        and hasattr(self.stats, "note_dispatch"):
                    # dispatch-budget observability: every supervised
                    # attempt is one device round-trip (launch + the
                    # host-blocking fetch the attempt ends in)
                    self.stats.note_dispatch(site)
                    self.stats.note_flush()
                with self.obs.span("device_batch", site=site,
                                   attempt=k, items=size):
                    result = self._attempt_once(site, attempt, size)
                    if validate is not None:
                        validate(result)
                _attempt_wall()
                self._consecutive[site] = 0
                self._note_clean_flush(site, size)
                if self.recloses:
                    # a successful device batch after a reclose IS the
                    # recovery the monitor promised — gate on this
                    self._count("res_recovered_batches")
                return result
            except GuardrailViolation as e:
                _attempt_wall()
                self._count("res_guardrail_rejects")
                self._warn(f"{site}: device output rejected by "
                           f"guardrail ({e}); re-executing")
                last = e
            except DeadlineExceeded as e:
                _attempt_wall()
                self._count("res_deadline_timeouts")
                last = e
            except Exception as e:
                _attempt_wall()
                if is_oom_error(e):
                    # allocation failure: retrying the IDENTICAL shape
                    # is pointless and the backend is not sick — hand
                    # over to the bisection path, outside both the
                    # retry loop and the breaker's failure window
                    return self._handle_oom(site, e, bisect, fallback)
                last = e
            if self._note_failure(site, last):
                break   # breaker opened: stop burning retries
        return self._degrade(site, fallback, _detail(last), last)

    # ---- OOM: bisect, never trip ---------------------------------------
    def _handle_oom(self, site: str, err: BaseException,
                    bisect: BisectableBatch | None, fallback):
        """A device allocation failure: count it, demote the batch
        ceiling, and bisect when the site declared how — the breaker is
        NEVER charged (the backend is healthy; the shape was too big)
        and the host fallback is reached only when no smaller split can
        succeed."""
        self._count("res_oom_events")
        self.obs.event("oom", site=site, detail=_detail(err),
                       items=len(bisect.items) if bisect else None)
        self._ceiling_clean = 0   # an OOM restarts the ceiling's
        #                           re-promotion probation from zero
        if bisect is not None and len(bisect.items) > max(1, bisect.floor):
            self._demote_bucket(site, len(bisect.items))
            try:
                return self._bisect(site, bisect)
            except ResilienceError:
                raise  # --fallback=fail is fatal at any depth
            except Exception as e2:
                # a half exhausted its own policy (DeviceWorkFailed) or
                # the recombine failed: the WHOLE batch degrades here,
                # through the caller's fallback — halves never fall
                # back alone
                return self._degrade(site, fallback, _detail(e2), e2)
        self._warn(f"{site}: device allocation failed "
                   f"({_detail(err)}) and the batch cannot be split "
                   "further; degrading")
        return self._degrade(site, fallback, _detail(err), err)

    def _bisect(self, site: str, spec: BisectableBatch):
        """Split ``spec.items`` in half and re-run each half through
        the FULL supervised path (so halves keep retries, deadlines,
        guardrails, injection — and recursively bisect on further
        OOM), then recombine in item order."""
        items = spec.items
        mid = (len(items) + 1) // 2
        self._count("res_batch_splits")
        self.obs.event("batch_split", site=site, items=len(items),
                       halves=[mid, len(items) - mid])
        self._warn(f"{site}: bisecting {len(items)}-item batch into "
                   f"{mid}+{len(items) - mid} after device OOM")
        parts = []
        self._in_bisect += 1
        try:
            for sub in (items[:mid], items[mid:]):
                if not sub:
                    continue
                sub_spec = replace(spec, items=sub)
                validate = None
                if spec.validate_for is not None:
                    validate = (lambda r, _s=sub:
                                spec.validate_for(r, _s))
                r = self.run(
                    site,
                    (lambda _s=sub_spec: _s.attempt_for(_s.items)),
                    validate=validate,
                    fallback=None,   # a failed half raises
                    #  DeviceWorkFailed and the TOP-level _handle_oom /
                    #  caller owns the whole-batch degradation — a half
                    #  must never fall back alone (order would survive,
                    #  but the caller's fallback replays the full batch)
                    bisect=sub_spec if len(sub) > max(1, spec.floor)
                    else None,
                    size=len(sub))
                parts.append((sub, r))
        finally:
            self._in_bisect -= 1
        return spec.combine(parts)

    def _demote_bucket(self, site: str, failed_size: int) -> None:
        """An attempt over ``failed_size`` items OOMed: the rest of the
        run must stop launching that pow2 bucket.  The new ceiling is
        half the bucket that failed; only an actual lowering counts
        (recursive bisection demotes step by step, once per level)."""
        bucket = 1 << max(0, int(failed_size) - 1).bit_length()
        if self._ceiling_origin is None or bucket > self._ceiling_origin:
            # remember the largest bucket that ever failed: it is the
            # re-promotion's restore point (climbing back to it means
            # the demotion is fully probed away)
            self._ceiling_origin = bucket
        new = max(1, bucket // 2)
        if self.bucket_ceiling is None or new < self.bucket_ceiling:
            self.bucket_ceiling = new
            self._count("res_bucket_demotions")
            self.obs.event("bucket_demotion", site=site, ceiling=new,
                           failed_size=int(failed_size))
            self._warn(f"{site}: batch bucket ceiling demoted to "
                       f"{new} items for the rest of the run "
                       f"(device OOM at {failed_size})")

    def _note_clean_flush(self, site: str, size: int | None) -> None:
        """One SIZED supervised attempt succeeded while the bucket
        ceiling is demoted: advance the re-promotion probation.  After
        ``policy.repromote_after`` consecutive clean flushes the
        ceiling probation-raises ONE pow2 step — the up-transition of
        the OOM demotion, so a long run (or a long-lived serve
        process) that hit one memory ceiling does not pre-chunk every
        flush forever.  Guards keeping this bounded and honest:
        bisection halves are excluded (they succeed right after the
        OOM that demoted the ceiling); only flushes that actually FILL
        the current bucket count (``size * 2 > ceiling`` — a tiny
        flush under a big ceiling proves nothing about memory at the
        ceiling); climbing back to the bucket that originally OOMed
        RESTORES the ceiling to None rather than doubling forever; and
        any new OOM resets the probation AND re-demotes, so a
        genuinely tight ceiling just oscillates one probe per
        ``repromote_after`` flushes instead of thrashing."""
        if (self.bucket_ceiling is None or size is None
                or self._in_bisect or self.policy.repromote_after <= 0
                or size * 2 <= self.bucket_ceiling):
            return
        self._ceiling_clean += 1
        if self._ceiling_clean < self.policy.repromote_after:
            return
        old = self.bucket_ceiling
        new = old * 2
        self._ceiling_clean = 0
        self._count("res_bucket_repromotions")
        if self._ceiling_origin is not None \
                and new >= self._ceiling_origin:
            # fully probed back to the bucket that failed: the
            # demotion is retired, flushes stop pre-chunking entirely
            self.bucket_ceiling = None
            self.obs.event("bucket_repromotion", site=site,
                           ceiling=None, restored=True)
            self._warn(f"{site}: batch bucket ceiling RESTORED "
                       f"(probation passed back to the {old}-item "
                       "bucket; an OOM re-demotes it)")
            return
        self.bucket_ceiling = new
        self.obs.event("bucket_repromotion", site=site, ceiling=new,
                       restored=False)
        self._warn(f"{site}: batch bucket ceiling probation-raised "
                   f"{old} -> {new} items after "
                   f"{self.policy.repromote_after} consecutive clean "
                   "flushes (an OOM re-demotes it)")

    def _attempt_once(self, site: str, attempt, size: int | None = None):
        plan = self.faults

        def body():
            if plan is None:
                return attempt()
            kind = plan.draw(site)       # may raise InjectedKill
            if kind == "down":
                self._count("res_injected_faults")
                from pwasm_tpu.resilience.faults import InjectedOutage
                raise InjectedOutage(
                    f"injected backend outage at {site} (tunnel down — "
                    "scripted down= window)")
            if plan.oom_for(size):
                # the simulated memory ceiling: allocation fails before
                # any compute, like the real allocator — it DOMINATES a
                # drawn compute-stage kind (which never fires and is
                # not counted: exactly one count per observable fault),
                # while the outage above dominates the OOM (a dead
                # tunnel cannot even try to allocate)
                from pwasm_tpu.resilience.faults import InjectedOOM
                self._count("res_injected_faults")
                raise InjectedOOM(
                    f"injected RESOURCE_EXHAUSTED at {site}: batch of "
                    f"{size} items exceeds the simulated device memory "
                    f"ceiling ({plan.oom})")
            if kind is not None:
                self._count("res_injected_faults")
            if kind == "raise":
                from pwasm_tpu.resilience.faults import InjectedFault
                raise InjectedFault(f"injected device fault at {site}")
            if kind == "hang":
                # capped so an injected hang proves the deadline
                # machinery without stalling a deadline-less fast suite
                time.sleep(plan.effective_hang(self.policy.deadline_s))
            res = attempt()
            if kind in ("nan", "corrupt"):
                res = plan.corrupt(res, site, kind)
            return res

        deadline = self.policy.deadline_s
        if deadline is None:
            return body()
        # a hand-rolled DAEMON thread, not a ThreadPoolExecutor: pool
        # workers are non-daemon and joined by an atexit hook, so a
        # genuinely hung XLA call would still block interpreter exit —
        # exactly the stall the deadline exists to walk away from
        box: dict = {}

        def runner():
            try:
                box["ok"] = body()
            except BaseException as e:
                box["err"] = e

        t = threading.Thread(target=runner, daemon=True,
                             name=f"pwasm-{site}")
        t.start()
        t.join(deadline)
        if t.is_alive():
            raise DeadlineExceeded(
                f"{site}: batch exceeded the {deadline:g}s device "
                f"deadline") from None
        if "err" in box:
            raise box["err"]
        return box["ok"]

    # ---- failure accounting / breaker ----------------------------------
    def consecutive(self, site: str) -> int:
        """This site's current consecutive-failure window."""
        return self._consecutive.get(site, 0)

    def site_breaker_open(self, site: str) -> bool:
        return site in self._site_open

    def _note_failure(self, site: str, err: BaseException) -> bool:
        """Record one failed attempt at ``site``; returns True when a
        breaker (global or this site's) just opened (stop retrying)."""
        self._consecutive[site] = self.consecutive(site) + 1
        threshold = self.policy.threshold_for(site)
        if self.breaker_open or self.consecutive(site) < threshold:
            return False
        ok, why = self._probe_backend()
        if ok:
            # backend is reachable: the failures are computational
            # (bad batch, guardrail rejects) — half-open THIS SITE and
            # keep attempting rather than walling off a healthy device.
            # A site that keeps exhausting its window is its own
            # problem, though: after site_trip_limit half-opens its own
            # breaker trips so a persistently-failing program stops
            # burning retries while the other sites stay on device.
            self._consecutive[site] = 0
            self._half_opens[site] = self._half_opens.get(site, 0) + 1
            if self._half_opens[site] >= self.policy.site_trip_limit:
                self._site_open.add(site)
                # counted SEPARATELY from the global trip: operators
                # page on res_breaker_trips (dead backend); a site trip
                # on a healthy backend is a different, softer alarm
                self._count("res_site_breaker_trips")
                self.obs.event("site_breaker_trip", site=site,
                               half_opens=self._half_opens[site])
                self._warn(
                    f"{site}: {self._consecutive_msg(site)} for the "
                    f"{self._half_opens[site]}th time with a healthy "
                    "backend — SITE breaker OPEN, degrading this "
                    "site's device work to the host path for the rest "
                    "of the run")
                return True
            self.obs.event("site_breaker_half_open", site=site,
                           half_opens=self._half_opens[site])
            self._warn(f"{site}: {self._consecutive_msg(site)} but the "
                       "backend probes healthy; breaker half-open")
            return False
        self._open_breaker()
        # counted only when the breaker actually OPENS — a healthy-probe
        # half-open above is not a trip, and operators alert on this
        self._count("res_breaker_trips")
        self.obs.event("breaker_trip", site=site,
                       why=(why or "unreachable").strip())
        self._warn(f"{site}: {self._consecutive_msg(site)}; backend "
                   f"probe says: {why.strip() or 'unreachable'} — "
                   "circuit breaker OPEN, degrading device work to the "
                   "host path"
                   + (" until it probes healthy again"
                      if self.monitor is not None
                      else " for the rest of the run"))
        return True

    def _open_breaker(self) -> None:
        self.breaker_open = True
        if self._degraded_t0 is None:
            self._degraded_t0 = time.perf_counter()
        if self.monitor is not None:
            self.monitor.note_open()
        # a freshly-confirmed-dead backend invalidates any cached
        # healthy probe verdict (TTL marker) — sibling processes must
        # not inherit a stale "healthy" and hang on their first touch
        try:
            from pwasm_tpu.utils.backend import invalidate_probe_cache
            invalidate_probe_cache()
        except Exception:
            pass

    def _reclose(self) -> None:
        """The monitor confirmed recovery: reclose the global breaker
        and re-promote device work.  Per-site trip state resets too —
        the failures that opened the breaker belonged to the outage,
        not the sites."""
        self.breaker_open = False
        self.recloses += 1
        self._count("res_breaker_recloses")
        self.obs.event("breaker_reclose", recloses=self.recloses)
        self._flush_degraded_wall()
        self._consecutive.clear()
        self._half_opens.clear()
        self._site_open.clear()
        self._warn("backend recovered — circuit breaker RECLOSED, "
                   "re-promoting device work (degraded batch state "
                   "reset)")

    def _flush_degraded_wall(self) -> None:
        if self._degraded_t0 is not None:
            self._count("res_degraded_wall_s",
                        time.perf_counter() - self._degraded_t0)
            self._degraded_t0 = None

    def finalize_stats(self) -> None:
        """End-of-run accounting hook: a run that ENDS degraded still
        owes its open window to ``degraded_wall_s``."""
        self._flush_degraded_wall()

    # ---- checkpointed state --------------------------------------------
    def export_state(self) -> dict:
        """Breaker/monitor/fault-plan state for the ``<report>.ckpt``,
        written after every completed batch so a ``--resume`` after a
        kill inherits mid-outage state instead of re-tripping (and a
        scripted ``down=`` window continues where it stopped)."""
        st = {
            "breaker_open": self.breaker_open,
            "recloses": self.recloses,
            "site_open": sorted(self._site_open),
            "half_opens": dict(self._half_opens),
            "consecutive": {k: v for k, v in self._consecutive.items()
                            if v},
            "bucket_ceiling": self.bucket_ceiling,
            "bucket_clean_flushes": self._ceiling_clean,
            "bucket_demoted_from": self._ceiling_origin,
        }
        if self.faults is not None:
            st["fault_calls"] = self.faults._calls
        return st

    def restore_state(self, st: dict) -> None:
        """Inherit checkpointed breaker state on ``--resume`` (inverse
        of :meth:`export_state`).  Each field restores independently —
        one malformed field (older build, hand-edited ckpt) must drop
        only itself, not abort the rest: losing e.g. ``fault_calls``
        while keeping ``breaker_open`` would replay a scripted outage
        window from call 1 against an already-open breaker."""
        def field(restore):
            try:
                restore()
            except (TypeError, ValueError, AttributeError, KeyError):
                pass

        if st.get("breaker_open"):
            field(self._open_breaker)
        field(lambda: setattr(
            self, "recloses", int(st.get("recloses", 0) or 0)))
        field(lambda: setattr(
            self, "_site_open",
            {str(s) for s in st.get("site_open", [])}))
        field(lambda: setattr(
            self, "_half_opens",
            {str(k): int(v) for k, v
             in dict(st.get("half_opens", {})).items()}))
        field(lambda: setattr(
            self, "_consecutive",
            {str(k): int(v) for k, v
             in dict(st.get("consecutive", {})).items()}))
        if st.get("bucket_ceiling") is not None:
            # a demoted batch ceiling is a fact about the DEVICE, not
            # the killed process: a --resume must not re-OOM its way
            # back down to it one bisection at a time
            field(lambda: setattr(
                self, "bucket_ceiling",
                max(1, int(st["bucket_ceiling"]))))
        # the re-promotion probation rides along: a --resume (or the
        # next warm-service job) continues the clean-flush count and
        # keeps the restore point instead of restarting the probation
        field(lambda: setattr(
            self, "_ceiling_clean",
            max(0, int(st.get("bucket_clean_flushes", 0) or 0))))
        if st.get("bucket_demoted_from") is not None:
            field(lambda: setattr(
                self, "_ceiling_origin",
                max(1, int(st["bucket_demoted_from"]))))
        if self.faults is not None and "fault_calls" in st:
            field(lambda: setattr(
                self.faults, "_calls", int(st["fault_calls"])))

    def _consecutive_msg(self, site: str) -> str:
        return (f"{self.policy.threshold_for(site)} consecutive device "
                "failures")

    def _probe_backend(self) -> tuple[bool, str]:
        if self.faults is not None:
            # scripted outage windows dominate every other verdict —
            # the probe must look dead INSIDE the window (so the
            # breaker can open on a healthy CI backend) and healthy
            # outside it (so the monitor can reclose)
            why = self.faults.outage_probe()
            if why is not None:
                return False, why
        if self._probe is not None:
            return self._probe()
        # a REAL bounded subprocess probe, not device_backend_reachable:
        # that gate short-circuits to healthy whenever jax is already
        # initialized in-process (always true by the time a mid-run
        # batch fails) and serves TTL-cached verdicts — either would
        # report a freshly-dead tunnel as healthy and the breaker could
        # never open
        import os
        if os.environ.get("PWASM_DEVICE_PROBE", "1") == "0":
            # probing disabled: treat the backend as healthy, so the
            # breaker only half-opens (same opt-out contract as the
            # CLI's startup gate)
            return True, ""
        from pwasm_tpu.utils.backend import probe_backend
        try:
            timeout = float(os.environ.get(
                "PWASM_DEVICE_PROBE_TIMEOUT", "150"))
        except ValueError:
            timeout = 150.0
        platform, why = probe_backend(dict(os.environ), timeout)
        return platform is not None, why

    def note_degraded(self, site: str, detail: str) -> None:
        """Record a CALLER-owned degradation — the ``DeviceWorkFailed``
        path, where the host fallback lives at the call site (e.g. the
        realign host oracle, the refine host phases).  Keeps the
        observability contract: every degradation counts toward
        ``res_fallbacks`` and leaves one stderr line, whichever side
        executes the fallback."""
        self._count("res_fallbacks")
        self.obs.event("fallback", site=site, reason=detail)
        self._warn(f"{site}: {detail}")

    # ---- degradation ----------------------------------------------------
    def _degrade(self, site: str, fallback, reason: str,
                 err: BaseException | None):
        if self.policy.fallback == "fail":
            raise ResilienceError(
                f"Error: device work '{site}' failed and --fallback="
                f"fail forbids degrading ({reason})\n") from err
        if fallback is not None:
            self._count("res_fallbacks")
            self.obs.event("fallback", site=site, reason=reason)
            self._warn(f"{site}: degrading batch to the host path "
                       f"({reason})")
            return fallback()
        # no fallback callable: the caller owns (and counts) the
        # degradation — see e.g. device_report.scalar_replay
        raise DeviceWorkFailed(f"{site}: {reason}") from err


def _detail(e: BaseException | None) -> str:
    if e is None:
        return "no attempt made"
    from pwasm_tpu.utils import exc_detail
    return exc_detail(e)
