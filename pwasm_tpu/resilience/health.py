"""Backend health monitoring: recovery from an open global breaker.

PR 1's circuit breaker handles the *down*-transition of a flapping
backend: after a probe-confirmed dead backend the GLOBAL breaker opens
and every supervised site degrades to its bit-exact host path.  That
used to be terminal — a 2-minute tunnel blip at batch 10 of a 10k-batch
run walled the remaining 9,990 batches on the CPU path forever.  The
:class:`BackendHealthMonitor` supplies the *up*-transition:

- once the global breaker opens, the monitor re-probes the backend via
  the existing bounded ``probe_backend`` on a capped-exponential
  schedule (``--reprobe-interval`` start, doubling on each unhealthy
  probe up to ``--reprobe-max``) — a dead backend costs a handful of
  bounded probes per hour, never a poll storm;
- recovery needs hysteresis, or one lucky probe in the middle of a
  flap storm would bounce the run between paths: ``hysteresis``
  consecutive healthy probes move the breaker open -> half-open ->
  closed (the classic three-state breaker), and any unhealthy probe in
  half-open falls straight back to open with the backoff re-doubled;
- on the reclose, :meth:`BatchSupervisor._reclose` routes subsequent
  batches back to the device (mid-run CPU->device re-promotion, the
  mirror of the device->CPU degradation) and resets the per-site trip
  state — the failures that opened the breaker were the outage's, not
  the sites'.

``--recover=off`` opts out: the breaker stays terminal (PR 1 behavior),
for operators who prefer a degraded-but-steady run over path flapping.

Every probe and transition is counted on the shared ``RunStats``
(``reprobe_attempts``, ``breaker_recloses``, ``degraded_batches``,
``recovered_batches``, ``degraded_wall_s``) and surfaces in the
``--stats`` JSON ``resilience`` block.

:func:`wait_for_backend` reuses the same schedule standalone — it is
how ``qa/chip_burst.py --wait`` blocks (bounded) for the first healthy
tunnel window instead of exiting 3.
"""

from __future__ import annotations

import sys
import time

# monitor states (the classic breaker triple, from the breaker's
# point of view: OPEN = degraded, CLOSED = recovered)
OPEN = "open"
HALF_OPEN = "half-open"
CLOSED = "closed"


class BackendHealthMonitor:
    """Schedules bounded re-probes of a dead backend and decides when
    the global breaker may reclose.

    ``probe`` is a ``() -> (ok, why)`` callable — normally the
    supervisor's ``_probe_backend`` (bounded subprocess probe, fault
    plan consulted first so scripted outages dominate).  ``clock`` is
    injectable for deterministic tests (defaults to
    ``time.monotonic``).  The monitor never sleeps: :meth:`poll` is
    called once per degraded batch and probes only when the schedule
    says it is time, so a run with no work between probes just stays
    degraded longer.
    """

    def __init__(self, probe=None, interval_s: float = 5.0,
                 max_interval_s: float = 300.0, hysteresis: int = 2,
                 stats=None, stderr=None, clock=None, obs=None):
        from pwasm_tpu.obs import NULL_OBS
        self.probe = probe
        self.interval_s = max(0.0, float(interval_s))
        self.max_interval_s = max(self.interval_s, float(max_interval_s))
        self.hysteresis = max(1, int(hysteresis))
        self.stats = stats
        self.stderr = stderr if stderr is not None else sys.stderr
        self.obs = obs if obs is not None else NULL_OBS
        self._clock = clock or time.monotonic
        self.state = CLOSED
        self._streak = 0          # consecutive healthy probes
        self._backoff = self.interval_s
        self._next_probe = 0.0

    # ---- counters ------------------------------------------------------
    def _count(self, name: str, n=1) -> None:
        if self.stats is not None and hasattr(self.stats, name):
            setattr(self.stats, name, getattr(self.stats, name) + n)

    def _warn(self, msg: str) -> None:
        print(f"pwasm: {msg}", file=self.stderr)

    # ---- lifecycle -----------------------------------------------------
    def attach(self, stats=None, stderr=None,
               obs=None) -> "BackendHealthMonitor":
        """Re-bind the per-run sinks and return self.  A warm serve
        process shares ONE monitor (one probe schedule, one
        open/half-open/closed state) across consecutive jobs, but each
        job owns its RunStats and stderr — the daemon re-attaches them
        at job start so reprobe/reclose counters land on the job that
        observed them.  The probe callable is also dropped: each job's
        supervisor re-wires its own (fault-plan-aware) probe, and a
        stale one would consult a finished job's fault plan.  The obs
        sink is ALWAYS rebound (to the given one or the null sink) —
        a finished job's closed event log must never receive the next
        job's transitions."""
        from pwasm_tpu.obs import NULL_OBS
        if stats is not None:
            self.stats = stats
        if stderr is not None:
            self.stderr = stderr
        self.obs = obs if obs is not None else NULL_OBS
        self.probe = None
        return self

    def note_open(self) -> None:
        """The global breaker just opened (or was restored open from a
        checkpoint): arm the re-probe schedule from its base interval."""
        self.state = OPEN
        self._streak = 0
        self._backoff = self.interval_s
        self._next_probe = self._clock() + self._backoff

    def next_probe_in(self) -> float:
        """Seconds until the next scheduled probe (<= 0: due now)."""
        return self._next_probe - self._clock()

    def poll(self) -> bool:
        """One recovery decision for one degraded batch.  Returns True
        exactly when the breaker may reclose NOW (hysteresis met); the
        caller owns the actual reclose.  Probes at most once per call,
        and only when the schedule is due."""
        if self.state == CLOSED:
            return True
        if self._clock() < self._next_probe:
            return False
        ok, why = self.probe() if self.probe is not None else (False, "")
        self._count("res_reprobe_attempts")
        self.obs.event("reprobe", ok=ok,
                       why=(why or "").strip() or None,
                       state=self.state)
        # schedule from the POST-probe clock: a real probe of a hung
        # tunnel blocks for its full subprocess timeout (150 s default),
        # far past any early backoff step — timed from the pre-probe
        # instant the schedule would already be due again on return and
        # every degraded batch would stall on a back-to-back inline
        # probe, exactly the poll storm the backoff exists to prevent
        now = self._clock()
        if not ok:
            if self.state == HALF_OPEN:
                self._warn("backend re-probe unhealthy in half-open "
                           f"({(why or '').strip() or 'unreachable'}); "
                           "breaker back to open")
            self.state = OPEN
            self._streak = 0
            # capped exponential: each unhealthy probe doubles the wait
            # (min 1 s step so interval 0 — poll-every-batch in tests —
            # cannot wedge the doubling at zero forever on real runs
            # where it matters; with interval 0 the cap stays 0 too, so
            # tests keep probe-per-batch determinism)
            if self.interval_s > 0:
                self._backoff = min(max(self._backoff * 2, 1.0),
                                    self.max_interval_s)
            self._next_probe = now + self._backoff
            return False
        self._streak += 1
        if self._streak == 1 and self.state == OPEN:
            self.state = HALF_OPEN
            self.obs.event("breaker_half_open", streak=self._streak,
                           hysteresis=self.hysteresis)
            self._warn("backend re-probe healthy; breaker half-open "
                       f"({self._streak}/{self.hysteresis} consecutive "
                       "healthy probes needed)")
        if self._streak >= self.hysteresis:
            self.state = CLOSED
            self._backoff = self.interval_s
            return True
        # healthy but hysteresis unmet: re-probe at the base interval,
        # not the backed-off one — the backend looks alive, confirm fast
        self._next_probe = now + self.interval_s
        return False


def wait_for_backend(budget_s: float, interval_s: float = 15.0,
                     max_interval_s: float = 120.0, hysteresis: int = 1,
                     probe=None, stderr=None) -> bool:
    """Block (bounded by ``budget_s`` seconds) until the backend probes
    healthy, on the monitor's capped-exponential schedule.  Returns
    True on the first healthy window, False when the budget ran out —
    the ``qa/chip_burst.py --wait`` primitive.  ``probe`` defaults to
    the real bounded ``probe_backend`` under the current env."""
    import os

    stderr = stderr if stderr is not None else sys.stderr
    if probe is None:
        from pwasm_tpu.utils.backend import probe_backend

        def probe():
            try:
                timeout = float(os.environ.get(
                    "PWASM_DEVICE_PROBE_TIMEOUT", "150"))
            except ValueError:
                timeout = 150.0
            platform, why = probe_backend(dict(os.environ), timeout)
            return platform is not None, why

    mon = BackendHealthMonitor(probe=probe, interval_s=interval_s,
                               max_interval_s=max_interval_s,
                               hysteresis=hysteresis, stderr=stderr)
    deadline = time.monotonic() + max(0.0, float(budget_s))
    mon.note_open()
    mon._next_probe = time.monotonic()   # first probe immediately
    while True:
        if mon.poll():
            return True
        now = time.monotonic()
        if now >= deadline:
            return False
        time.sleep(max(0.0, min(mon.next_probe_in(), deadline - now)))
