"""Resilient device execution (SURVEY.md §5 failure handling, grown up).

The reference fails fast — one ``GError`` and the process exits
(core/errors.py mirrors it).  That is the right model for a CLI
one-shot and the wrong one for a batch engine serving heavy traffic:
a transient device fault mid-run must not discard hours of completed
work.  This package supplies the three layers the device pipeline
threads through:

- ``faults``      deterministic, seeded fault injection (raise / hang /
                  NaN / corrupt) armed by ``--inject-faults=SPEC`` or
                  ``PWASM_INJECT_FAULTS`` — the harness that proves the
                  rest of the package works before real hardware does;
- ``supervisor``  per-batch deadlines, bounded retry with exponential
                  backoff + jitter, and a circuit breaker that degrades
                  device work to the CPU path (policy ``--fallback=cpu``)
                  or aborts loudly (``--fallback=fail``);
- ``guardrails``  cheap invariant validation of device outputs, so
                  silent corruption is treated as a device fault and
                  re-executed instead of written into the report;
- ``health``      recovery from an open global breaker: bounded
                  re-probes on a capped-exponential schedule with
                  hysteresis (``--reprobe-interval``/``--reprobe-max``,
                  ``--recover=auto|off``) reclose the breaker and
                  re-promote device work mid-run — the up-transition of
                  a flapping backend, mirroring the supervisor's
                  down-transition.

Counters flow into ``utils.runstats`` under the ``resilience`` block of
the ``--stats`` JSON.
"""

from pwasm_tpu.resilience.faults import (  # noqa: F401
    FaultPlan, InjectedFault, InjectedKill, InjectedOOM, InjectedOutage,
    parse_fault_spec)
from pwasm_tpu.resilience.health import (  # noqa: F401
    BackendHealthMonitor, wait_for_backend)
from pwasm_tpu.resilience.guardrails import GuardrailViolation  # noqa: F401
from pwasm_tpu.resilience.lifecycle import (  # noqa: F401
    PreemptedError, SignalDrain)
from pwasm_tpu.resilience.supervisor import (  # noqa: F401
    BatchSupervisor, BisectableBatch, DeadlineExceeded, DeviceWorkFailed,
    ResilienceError, ResiliencePolicy, is_oom_error)
