"""Run configuration.

The reference holds these as globals plus two library statics
(pafreport.cpp:30-46, GapAssem.cpp:5-6); here everything is threaded through
one config object.  The methylation-motif table is configurable (the
reference hardcodes it with a TODO to externalize, pafreport.cpp:39-41).
"""

from __future__ import annotations

from dataclasses import dataclass, field

DEFAULT_MOTIFS = ("CCTGG", "CCAGG", "GATC", "GTAC")

# Gene-CDS vs full-genome auto-selection threshold: query FASTA *file size*
# in bytes (pafreport.cpp:253-262; quirk SURVEY.md §2.5.7).
AUTO_FULLGENOME_FASTA_BYTES = 120000


@dataclass
class Config:
    debug: bool = False
    verbose: bool = False
    fullgenome: bool = False        # -F: keep every query-target alignment
    gene_cds: bool = False          # -G: first alignment per pair only
    skip_codan: bool = False        # -N / auto: skip codon-impact analysis
    remove_cons_gaps: bool = False  # pafreport forces this off (quirk §2.5.8)
    refine_clipping: bool = True    # MSAColumns::refineClipping default
    clipmax: float = 0.0            # -c: absolute bases (>1) or fraction
    motifs: tuple[str, ...] = field(default=DEFAULT_MOTIFS)

    # TPU-path knobs (no reference equivalent)
    device: str = "cpu"             # cpu | tpu
    band: int = 64                  # banded-DP band width
    batch: int = 256                # device batch size
    realign: bool = False           # --realign: DP traceback gaps for MSA
    shard: int = 0                  # --shard[=N]: mesh over N devices
    #                                 (0 = off, -1 = all visible devices)

    # run-control / observability knobs (SURVEY.md §5; no ref equivalent)
    skip_bad_lines: bool = False    # warn + continue on malformed lines
    resume: bool = False            # append to -o, skipping emitted alns
    profile_dir: str = ""           # jax.profiler trace output directory
    stats_path: str = ""            # write run-stats JSON here
    trace_json: str = ""            # --trace-json: Chrome trace-event
    #                                 JSON of the host-side phase spans
    log_json: str = ""              # --log-json: NDJSON run-lifecycle
    #                                 event log ("-" = stdout)
    metrics_textfile: str = ""      # --metrics-textfile: Prometheus
    #                                 text exposition, written atomically
    #                                 at end of run (pwasm_tpu.obs)
    trace_max_events: int = 0       # --trace-max-events: span-recorder
    #                                 event cap (0 = the 200k default)
    log_json_max_bytes: int = 0     # --log-json-max-bytes: size-capped
    #                                 event-log rotation (0 = unbounded)
    compile_cache_dir: str = ""     # --compile-cache-dir: persistent
    #                                 XLA compilation cache location
    #                                 (via the jaxcompat shim; "" =
    #                                 the PWASM_JAX_CACHE_DIR/default)

    # resilience knobs (pwasm_tpu.resilience; no ref equivalent —
    # the reference fails fast, SURVEY.md §2.5.12)
    max_retries: int = 2            # --max-retries: device re-attempts
    device_deadline: float = 0.0    # --device-deadline: s per batch
    #                                 attempt (0 = unbounded)
    deadline_s: float = 0.0         # --deadline-s: END-TO-END wall
    #                                 budget for the whole run (0 =
    #                                 unbounded).  Expiry requests a
    #                                 graceful drain at the next batch
    #                                 boundary: valid resumable ckpt,
    #                                 rc 75, reason "deadline_exceeded"
    #                                 (ISSUE 18, docs/RESILIENCE.md)
    fallback: str = "cpu"           # --fallback: cpu (degrade) | fail
    inject_faults: str = ""         # --inject-faults=SPEC (debug)
    recover: str = "auto"           # --recover: auto (re-probe an open
    #                                 global breaker and re-promote
    #                                 device work on reclose) | off
    #                                 (an open breaker is terminal)
    reprobe_interval: float = 5.0   # --reprobe-interval: first re-probe
    #                                 delay after the breaker opens (s)
    reprobe_max: float = 300.0      # --reprobe-max: capped-exponential
    #                                 re-probe schedule ceiling (s)


def load_motifs(path: str) -> tuple[str, ...]:
    """Load a motif table: one motif per line, '#' comments allowed.
    Motifs are DNA strings, so the file must be ASCII text — opening with
    ``encoding="ascii"`` keeps the native binary's byte-oriented reader
    and this one in exact agreement (both reject non-ASCII content)."""
    from .errors import PwasmError

    out = []
    try:
        with open(path, encoding="ascii") as f:
            for line in f:
                line = line.strip().upper()
                if line and not line.startswith("#"):
                    out.append(line)
    except UnicodeDecodeError as e:
        raise PwasmError(
            f"Error: motif file {path} is not ASCII text ({e})") from e
    return tuple(out)
