"""PAF record parsing.

Mirrors the reference's per-line handling: tab-split with >=15 fields
required (pafreport.cpp:307-309), core coordinates lifted into an AlnInfo
struct (pafreport.cpp:54-88), and the tag scan over fields 12+ for
``NM:i:``, ``AS:i:``, ``cg:Z:``, ``cs:Z:`` with first-hit-wins semantics
(pafreport.cpp:492-520).  A missing/empty CIGAR is fatal (pafreport.cpp:521).
The reference never validates the presence of ``cs`` (it would crash on a
NULL pointer, SURVEY.md §2.5.4); we raise a clear error instead — the input
contract is unchanged (PAF must come from ``minimap2 -c --cs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from pwasm_tpu.core.errors import PwasmError


_ASCII_DIGITS = frozenset("0123456789")


def _atoi(s: str) -> int:
    """C atoi semantics: optional sign + leading ASCII digits; 0 on junk.

    Restricted to ASCII digits — ``str.isdigit`` accepts unicode digit
    forms that ``int()`` rejects, which would turn junk input into a crash
    instead of atoi's tolerant 0."""
    s = s.strip()
    i = 0
    if i < len(s) and s[i] in "+-":
        i += 1
    j = i
    while j < len(s) and s[j] in _ASCII_DIGITS:
        j += 1
    if j == i:
        return 0
    return int(s[:j])


@dataclass
class AlnInfo:
    """One PAF line's core fields (reference: AlnInfo, pafreport.cpp:54-88)."""

    reverse: int = 2
    r_id: str = ""
    r_len: int = 0
    r_alnstart: int = 0
    r_alnend: int = 0
    t_id: str = ""
    t_len: int = 0
    t_alnstart: int = 0
    t_alnend: int = 0

    @classmethod
    def from_fields(cls, fields: list[str]) -> "AlnInfo":
        return cls(
            reverse=1 if fields[4] == "-" else 0,
            r_id=fields[0],
            r_len=_atoi(fields[1]),
            r_alnstart=_atoi(fields[2]),
            r_alnend=_atoi(fields[3]),
            t_id=fields[5],
            t_len=_atoi(fields[6]),
            t_alnstart=_atoi(fields[7]),
            t_alnend=_atoi(fields[8]),
        )


@dataclass
class PafRecord:
    """A parsed PAF line: AlnInfo + the tags the pipeline consumes."""

    alninfo: AlnInfo
    fields: list[str] = field(default_factory=list)
    edist: int = -1       # NM:i:
    alnscore: int = 0     # AS:i:
    cigar: str | None = None   # cg:Z:
    cs: str | None = None      # cs:Z:

    @property
    def line(self) -> str:
        return "\t".join(self.fields)


def parse_paf_line(line: str) -> PafRecord:
    """Parse one PAF line (must have >=15 tab-separated fields)."""
    fields = line.rstrip("\n").split("\t")
    if len(fields) < 15:
        raise PwasmError(
            f"Error: invalid PAF fline (num. fields={len(fields)}):\n{line}\n"
        )
    rec = PafRecord(alninfo=AlnInfo.from_fields(fields), fields=fields)
    got = 0
    gotall = 1 + 2 + 4 + 8
    for f in fields[12:]:
        if f.startswith("NM:i:"):
            rec.edist = _atoi(f[5:])
            got |= 1
        elif f.startswith("AS:i:"):
            rec.alnscore = _atoi(f[5:])
            got |= 2
        elif f.startswith("cg:Z:"):
            rec.cigar = f[5:]
            got |= 4
        elif f.startswith("cs:Z:"):
            rec.cs = f[5:]
            got |= 8
        if got == gotall:
            break
    return rec
