"""Host-side data model: DNA tables, FASTA access, PAF/cs/CIGAR parsing,
diff-event extraction."""

from pwasm_tpu.core.dna import (  # noqa: F401
    revcomp,
    encode,
    decode,
    translate_codon,
    CODE_A,
    CODE_C,
    CODE_G,
    CODE_T,
    CODE_N,
    CODE_GAP,
)
from pwasm_tpu.core.errors import PwasmError, ParseError  # noqa: F401
from pwasm_tpu.core.paf import PafRecord, AlnInfo, parse_paf_line  # noqa: F401
from pwasm_tpu.core.fasta import FastaFile  # noqa: F401
from pwasm_tpu.core.events import GapData, DiffEvent, PafAlignment  # noqa: F401
