"""Error model.

The reference fails fast with distinct exit codes (SURVEY.md §2.5.12):
usage/argument errors exit 1, a zero-coverage MSA column exits 5
(GapAssem.cpp:1121-1131), and generic fatal errors (GError) use the
default exit code.  NB the reference DECLARES a parse-error path exiting
3 (PAFAlignment::parseErr, pafreport.cpp:463-467) but never calls it —
every actual parse failure goes through GError (pafreport.cpp:521-718)
and exits 1.  We mirror that faithfully: ``ParseError`` exists as the
parseErr analog but the extractors raise plain ``PwasmError`` (exit 1),
exactly like the reference's live code path.
"""

from __future__ import annotations

EXIT_USAGE = 1
EXIT_FATAL = 1  # GError's default exit status
EXIT_PARSE = 3
EXIT_ZERO_COVERAGE = 5
# Ours, not the reference's: a run that caught SIGTERM/SIGINT (or the
# scripted preempt= fault leg), drained its in-flight batch, flushed a
# final checkpoint, and exited RESUMABLE — sysexits.h EX_TEMPFAIL, the
# conventional "temporary failure; retry" status, which is exactly what
# a preempted-but-checkpointed batch run is (--resume completes it).
EXIT_PREEMPTED = 75


class PwasmError(Exception):
    """Fatal error (the reference's GError): message + process exit code."""

    exit_code = EXIT_FATAL

    def __init__(self, message: str, exit_code: int | None = None):
        super().__init__(message)
        if exit_code is not None:
            self.exit_code = exit_code


class ParseError(PwasmError):
    """Malformed alignment line (reference: PAFAlignment::parseErr,
    exit 3).  Like parseErr itself — which the reference declares but
    never calls (every live parse failure GErrors with exit 1) — this
    class is API surface, intentionally unraised by the extractors."""

    exit_code = EXIT_PARSE


class ZeroCoverageError(PwasmError):
    """A zero-coverage column inside an MSA (reference: ErrZeroCov, exit 5)."""

    exit_code = EXIT_ZERO_COVERAGE
