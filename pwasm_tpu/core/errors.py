"""Error model.

The reference fails fast with distinct exit codes (SURVEY.md §2.5.12):
usage/argument errors exit 1, alignment parse errors exit 3
(pafreport.cpp:463-467), a zero-coverage MSA column exits 5
(GapAssem.cpp:1121-1131), and generic fatal errors (GError) use the default
exit code. We mirror those codes so scripted callers behave identically.
"""

from __future__ import annotations

EXIT_USAGE = 1
EXIT_FATAL = 1  # GError's default exit status
EXIT_PARSE = 3
EXIT_ZERO_COVERAGE = 5


class PwasmError(Exception):
    """Fatal error (the reference's GError): message + process exit code."""

    exit_code = EXIT_FATAL

    def __init__(self, message: str, exit_code: int | None = None):
        super().__init__(message)
        if exit_code is not None:
            self.exit_code = exit_code


class ParseError(PwasmError):
    """Malformed alignment line (reference: PAFAlignment::parseErr, exit 3)."""

    exit_code = EXIT_PARSE


class ZeroCoverageError(PwasmError):
    """A zero-coverage column inside an MSA (reference: ErrZeroCov, exit 5)."""

    exit_code = EXIT_ZERO_COVERAGE
