"""Per-alignment diff extraction: the ``cs``-string and CIGAR walks.

This is the ground-truth layer (reference: PAFAlignment constructor,
pafreport.cpp:477-719).  For each PAF line it

1. scans the tags (done upstream in ``pwasm_tpu.core.paf``),
2. walks the ``cs`` string to *reconstruct the target sequence* from the
   reference query and record diff events (pafreport.cpp:526-643),
3. walks the CIGAR to collect ref/target gap positions
   (pafreport.cpp:644-714), and
4. cross-validates reconstructed lengths against the PAF coordinates
   (pafreport.cpp:715-718).

Behavioral parity notes (SURVEY.md §2.5):

- Adjacent substitutions merge into one multi-base S event; on the reverse
  strand they are merged in RC space and un-flipped afterwards (§2.5.5).
- The reconstructed target keeps the reference's case convention: matched
  bases are upper-case (copied from the upper-cased query), substituted and
  inserted bases lower-case — the case leaks into the reported target
  context, so it is observable behavior.
- ``~`` (splice) and unknown ops are fatal; a ``cs`` base that contradicts
  the query FASTA is fatal (§2.5.11).
- Reverse-strand events are recorded against the RC'd query then post-fixed
  into forward coordinates (pafreport.cpp:628-643).
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.paf import AlnInfo, PafRecord

CS_ERROR = "Error parsing cs string from line: {} (cs position: {})\n"
CIGAR_ERROR = "Error parsing cigar string from line: {} (cigar position: {})\n"
SOFTCLIP_WARNING = ("Warning: soft clipping shouldn't be found in this "
                    "application!")
BASE_MISMATCH_ERROR = ("Error: base mismatch {} != qstr[{}] ({}) at line"
                       "\n{}\n")
SPLICE_ERROR = "Error: spliced alignments not supported! at line:\n{}\n"
COORDS_ERROR = ("Error: invalid alignment coordinates "
                "(q {}-{}/{}, t {}-{}) at line:\n{}\n")


def validate_coords(al, line: str) -> None:
    """Coordinate sanity shared by BOTH extractors: corrupted fields
    (negative or inverted spans) must fail as a clean PwasmError, not
    as allocation blow-ups or out-of-bounds reference reads (found by
    fuzzing mutated PAF lines; the reference would GMALLOC the bogus
    size and crash too — our --skip-bad-lines contract needs a clean
    error).  Only what memory safety requires: the query bounds feed
    the offset math (r_len - r_alnend on reverse strands) and the
    reference reads; the target span sizes the reconstruction buffer.
    The PAF t_len column is NOT checked against — the reference never
    reads it, and inputs with a junk t_len but self-consistent spans
    extract identically there."""
    if not (0 <= al.r_alnstart <= al.r_alnend <= al.r_len
            and 0 <= al.t_alnstart <= al.t_alnend):
        raise PwasmError(COORDS_ERROR.format(
            al.r_alnstart, al.r_alnend, al.r_len,
            al.t_alnstart, al.t_alnend, line))
CS_OP_ERROR = "Error: unhandled event at {} in cs, line:\n{}\n"
CIGAR_OP_ERROR = "Error: unhandled cigar_op {} (len {}) in {}\n"
TSEQ_LEN_ERROR = ("Error: tseq alignment length mismatch ({} vs {}({}-{}))"
                  " at line:{}\n")
REF_LEN_ERROR = ("Error: ref alignment length mismatch ({} vs {}-{}) at "
                 "line:{}\n")


@dataclass(slots=True)
class GapData:
    """(pos, len) gap record (reference: GapData, pafreport.cpp:48-52)."""

    pos: int = 0
    len: int = 1


# slots: tens of thousands of events materialize per realistic-scale
# report batch — slotted instances construct ~30% faster and index
# ~20% faster in the columnar assembly hot loop
@dataclass(slots=True)
class DiffEvent:
    """One indel/substitution event (reference: TDiffInfo,
    pafreport.cpp:90-132).

    ``evt`` is 'S' (substitution), 'I' (insertion in target) or 'D'
    (deletion from target); ``rloc`` is the event position on the forward
    query; ``tloc`` the position within the aligned target region on the
    aligned strand (flipped for display on reverse); ``tctx`` the target
    context (event ± 5 bases, case as reconstructed)."""

    evt: str = ""
    evtlen: int = 0
    evtbases: bytes = b""
    evtsub: bytes = b""
    rloc: int = 0
    tloc: int = 0
    tctx: bytes = b""

    def set_tcontext(self, tseq: bytes) -> None:
        """Fill ``tctx`` (reference: TDiffInfo::setTContext,
        pafreport.cpp:120-128; note the right-edge clamp drops the final
        target base — observable quirk preserved)."""
        tc_start = self.tloc - 5
        if tc_start < 0:
            tc_start = 0
        evt_len = 0 if self.evt == "D" else self.evtlen
        tc_end = self.tloc + evt_len + 5
        if tc_end >= len(tseq):
            tc_end = len(tseq) - 1
        self.tctx = bytes(tseq[tc_start:tc_end])


_ASCII_DIGITS = frozenset("0123456789")


def _parse_int(s: str, i: int) -> tuple[int, int]:
    """Parse an unsigned ASCII integer at s[i:]; return (value, next_index)
    or (-1, i) if no digits (the reference's parseInt failure path).  cs and
    CIGAR op counts are always unsigned — accepting a sign would let
    malformed counts cancel in the length cross-validation and yield corrupt
    negative-length gap records instead of a parse error."""
    k = i
    while k < len(s) and s[k] in _ASCII_DIGITS:
        k += 1
    if k == i:
        return -1, i
    return int(s[i:k]), k


@dataclass
class PafAlignment:
    """One parsed alignment: diff events + gap lists + reconstructed target.

    Reference: class PAFAlignment (pafreport.cpp:134-158, ctor 477-719).
    ``tseq`` is the reconstructed target over the aligned region, in the
    alignment orientation (RC space when ``reverse``), mixed case.
    """

    alninfo: AlnInfo
    rgaps: list[GapData] = field(default_factory=list)
    tgaps: list[GapData] = field(default_factory=list)
    tdiffs: list[DiffEvent] = field(default_factory=list)
    seqname: str = ""
    edist: int = -1
    alnscore: int = 0
    seqlen: int = 0
    offset: int = 0
    reverse: int = 0
    tseq: bytes = b""


def extract_alignment(rec: PafRecord, refseq_aln: bytes,
                      use_native: bool | None = None) -> PafAlignment:
    """Build a PafAlignment from a parsed PAF record.

    ``refseq_aln`` is the query sequence in *alignment orientation*: the
    forward upper-cased query, or its reverse complement when the PAF strand
    is '-' (the caller keeps both copies, mirroring pafreport.cpp:338-362).

    Dispatches to the native C++ extractor when available (parity enforced
    by tests/test_native.py); ``use_native=False`` forces the Python path.
    """
    validate_coords(rec.alninfo, rec.line)
    if use_native is None:
        use_native = os.environ.get("PWASM_NATIVE", "1") != "0"
    if use_native:
        from pwasm_tpu.native import extract_native

        aln = extract_native(rec, refseq_aln)
        if aln is not None:
            return aln
    al = rec.alninfo
    line = rec.line
    aln = PafAlignment(alninfo=al, seqname=al.t_id, reverse=al.reverse,
                       edist=rec.edist, alnscore=rec.alnscore)
    aln.offset = al.r_alnstart
    if al.reverse:  # offset on the reverse-complemented query string
        aln.offset = al.r_len - al.r_alnend
    aln.seqlen = al.t_alnend - al.t_alnstart
    if not rec.cigar:
        raise PwasmError(CIGAR_ERROR.format(line, 0))
    if rec.cs is None:
        raise PwasmError(CS_ERROR.format(line, 0))

    offset = aln.offset
    cs = rec.cs
    tseq = bytearray()
    tdiffs: list[DiffEvent] = []
    qpos = 0  # query position within the alignment (alignment orientation)
    tpos = 0  # target position within the aligned region
    eff_t_len = al.t_alnend - al.t_alnstart
    i = 0
    n = len(cs)
    # ---- cs walk: rebuild tseq and emit diff events (pafreport.cpp:536-626)
    while i < n:
        op = cs[i]
        i += 1
        if op == ":":
            cl, i2 = _parse_int(cs, i)
            if i2 == i:
                raise PwasmError(CS_ERROR.format(line, cs[i:]))
            i = i2
            if offset + qpos + cl > len(refseq_aln):
                # copy-match run goes past the query end (the native
                # extractor checks this too; keeps both paths identical)
                raise PwasmError(CS_ERROR.format(line, cs[i:]))
            tseq += refseq_aln[offset + qpos: offset + qpos + cl]
            qpos += cl
            tpos += cl
        elif op == "*":
            if i + 1 >= n:
                raise PwasmError(CS_ERROR.format(line, cs[i:]))
            tch = cs[i].upper()
            qch = cs[i + 1].upper()
            i += 2
            q_pos = offset + qpos
            if q_pos >= len(refseq_aln) or qch != chr(refseq_aln[q_pos]):
                refc = chr(refseq_aln[q_pos]) \
                    if q_pos < len(refseq_aln) else "?"
                raise PwasmError(
                    BASE_MISMATCH_ERROR.format(qch, q_pos, refc, line))
            # merge adjacent substitutions into a single event
            if (tdiffs and tdiffs[-1].evt == "S"
                    and tdiffs[-1].rloc == q_pos - len(tdiffs[-1].evtbases)):
                # NB: the reference leaves evtlen at 1 for merged multi-base
                # substitutions (pafreport.cpp:556-573) — that shortens the
                # reported target context window, an observable quirk we keep.
                tdiffs[-1].evtbases += tch.encode()
                tdiffs[-1].evtsub += qch.encode()
            else:
                tdiffs.append(DiffEvent("S", 1, tch.encode(), qch.encode(),
                                        rloc=q_pos, tloc=tpos))
            tseq += tch.lower().encode()
            qpos += 1
            tpos += 1
        elif op == "-":
            # gap in query => bases present only in the target (Insertion)
            s_pos = tpos
            while i < n and cs[i].isalpha():
                tseq.append(ord(cs[i].lower()))
                i += 1
                tpos += 1
            e_len = tpos - s_pos
            q_pos = offset + qpos
            ev = DiffEvent("I", e_len, bytes(tseq[-e_len:]) if e_len else b"",
                           b"", rloc=q_pos, tloc=s_pos)
            if al.reverse:
                ev.evtbases = revcomp(ev.evtbases)
                ev.rloc = al.r_len - q_pos
            tdiffs.append(ev)
        elif op == "+":
            # gap in target => query bases missing from the target (Deletion)
            s_pos = qpos
            while i < n and cs[i].isalpha():
                i += 1
                qpos += 1
            e_len = qpos - s_pos
            q_pos = s_pos + offset
            if q_pos + e_len > len(refseq_aln):
                # deleted-bases run goes past the query end (native parity)
                raise PwasmError(CS_ERROR.format(line, cs[i:]))
            ev = DiffEvent("D", e_len,
                           bytes(refseq_aln[q_pos:q_pos + e_len]), b"",
                           rloc=q_pos, tloc=tpos)
            if al.reverse:
                ev.evtbases = revcomp(ev.evtbases)
                ev.rloc = al.r_len - q_pos - e_len
            tdiffs.append(ev)
        elif op == "~":
            raise PwasmError(SPLICE_ERROR.format(line))
        else:
            # the reference reports from the position *after* the op char
            raise PwasmError(CS_OP_ERROR.format(cs[i:], line))

    # ---- context fill + reverse-strand fixups (pafreport.cpp:628-643)
    tseq_final = bytes(tseq)
    for ev in tdiffs:
        ev.set_tcontext(tseq_final)
        if al.reverse:
            ev.tctx = revcomp(ev.tctx)
            ev.tloc = len(tseq_final) - ev.tloc
            if ev.evt == "S":
                # substitutions were kept in RC space to simplify merging
                ev.evtbases = revcomp(ev.evtbases)
                ev.evtsub = revcomp(ev.evtsub)
                ev.rloc = al.r_len - ev.rloc - len(ev.evtbases)
    if al.reverse:
        tdiffs.reverse()
    aln.tdiffs = tdiffs
    aln.tseq = tseq_final

    # ---- CIGAR walk: gap positions (pafreport.cpp:644-714)
    cigar = rec.cigar
    qpos = 0
    tpos = 0
    i = 0
    n = len(cigar)
    while i < n:
        cl, i2 = _parse_int(cigar, i)
        if i2 == i:
            raise PwasmError(CIGAR_ERROR.format(line, cigar[i:]))
        i = i2
        if i >= n:
            raise PwasmError(CIGAR_ERROR.format(line, ""))
        cop = cigar[i]
        if cop in "XM=":
            tpos += cl
            qpos += cl
        elif cop in "PH":
            pass  # neither position advances
        elif cop == "S":
            # soft clip: shouldn't appear in this application
            # (reference warns on stderr, pafreport.cpp:675-679)
            print(f"{SOFTCLIP_WARNING}\n{line}", file=sys.stderr)
            qpos += cl
        elif cop == "I":
            # gap in the target sequence; tpos not advanced
            aln.tgaps.append(GapData(eff_t_len - tpos if al.reverse else tpos,
                                     cl))
            qpos += cl
        elif cop == "D":
            # gap in the query; tpos advances
            pos = offset + qpos
            if al.reverse:
                pos = al.r_len - pos
            aln.rgaps.append(GapData(pos, cl))
            tpos += cl
        elif cop == "N":
            # intron-style skip: treated as a query gap too
            tpos += cl
            pos = offset + qpos
            if al.reverse:
                pos = al.r_len - pos
            aln.rgaps.append(GapData(pos, cl))
        else:
            raise PwasmError(CIGAR_OP_ERROR.format(cop, cl, line))
        i += 1

    # ---- cross-validation (pafreport.cpp:715-718)
    if eff_t_len != tpos or len(tseq) != tpos:
        raise PwasmError(TSEQ_LEN_ERROR.format(
            tpos, eff_t_len, al.t_alnend, al.t_alnstart, line))
    if al.r_alnend - al.r_alnstart != qpos:
        raise PwasmError(REF_LEN_ERROR.format(
            qpos, al.r_alnend, al.r_alnstart, line))
    return aln
