"""Random-access FASTA reader (faidx-style).

Equivalent capability to the reference's gclib GFastaDb/GFastaIndex/GFaSeqGet
usage (pafreport.cpp:255,346): open a FASTA file, fetch whole records by id
without re-scanning the file.  The index is built in one streaming pass and
records byte offsets, so fetches are O(record size) seeks.

Also provides in-memory helpers used by tests and the MSA writers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from pwasm_tpu.core.errors import PwasmError


@dataclass
class _FaiEntry:
    name: str
    length: int  # number of sequence bytes (newlines excluded)
    offset: int  # byte offset of first sequence byte
    end: int     # byte offset one past the last sequence line


class FastaFile:
    """Indexed FASTA access by sequence id.

    >>> fa = FastaFile(path)
    >>> fa.fetch("gene1")      # -> bytes (no newlines), or None if absent
    >>> len(fa)                # number of records
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        self._index: dict[str, _FaiEntry] = {}
        self._order: list[str] = []
        self._build_index()

    def _build_index(self) -> None:
        # native streaming indexer when available (C++ one-pass scan,
        # bit-identical entries — parity enforced by tests/test_native.py)
        from pwasm_tpu.native import fasta_index
        try:
            entries = fasta_index(self.path)
        except OSError:
            entries = None  # fall through to the Python reader's error
        if entries is not None:
            for name, seqlen, start, end in entries:
                self._add(name, seqlen, start, end)
            if not self._index:
                raise PwasmError(f"Error: invalid FASTA file {self.path} !")
            return
        name = None
        seqlen = 0
        seq_start = 0
        pos = 0
        with open(self.path, "rb") as f:
            for line in f:
                linelen = len(line)
                if line.startswith(b">"):
                    if name is not None:
                        self._add(name, seqlen, seq_start, pos)
                    header = line[1:].strip()
                    name = header.split(None, 1)[0].decode() if header else ""
                    seqlen = 0
                    seq_start = pos + linelen
                elif name is not None:
                    # count exactly the bytes fetch() will return (all
                    # whitespace removed, not just line ends)
                    seqlen += len(line.translate(None, b" \t\r\n\v\f"))
                pos += linelen
            if name is not None:
                self._add(name, seqlen, seq_start, pos)
        if not self._index:
            raise PwasmError(f"Error: invalid FASTA file {self.path} !")

    def _add(self, name: str, seqlen: int, start: int, end: int) -> None:
        if name not in self._index:
            self._index[name] = _FaiEntry(name, seqlen, start, end)
            self._order.append(name)

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    @property
    def names(self) -> list[str]:
        return list(self._order)

    def length(self, name: str) -> int:
        return self._index[name].length

    def fetch(self, name: str) -> bytes | None:
        """Fetch a full record's sequence (newlines stripped), or None."""
        ent = self._index.get(name)
        if ent is None:
            return None
        from pwasm_tpu.native import fasta_fetch
        try:
            raw_n = fasta_fetch(self.path, ent.offset, ent.end)
        except OSError:
            raw_n = None
        if raw_n is not None:
            return raw_n
        with open(self.path, "rb") as f:
            f.seek(ent.offset)
            raw = f.read(ent.end - ent.offset)
        # strip ALL whitespace, matching the per-line strip() used when
        # indexing — otherwise length() and fetch() disagree on files with
        # trailing blanks and stray bytes later encode as phantom Ns
        return bytes(raw.translate(None, b" \t\r\n\v\f"))

    def file_size(self) -> int:
        """Size of the FASTA file in bytes.

        The reference auto-selects full-genome mode when this exceeds 120000
        bytes (pafreport.cpp:253-262, quirk SURVEY.md §2.5.7) — by *file
        size*, not sequence length; we preserve that contract.
        """
        return os.path.getsize(self.path)


def write_fasta(path: str, records: list[tuple[str, bytes]], width: int = 60) -> None:
    """Write records as FASTA with the given line width (test helper)."""
    with open(path, "w") as f:
        for name, seq in records:
            f.write(f">{name}\n")
            s = seq.decode() if isinstance(seq, (bytes, bytearray)) else seq
            for i in range(0, len(s), width):
                f.write(s[i:i + width] + "\n")
