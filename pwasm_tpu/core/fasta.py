"""Random-access FASTA reader (faidx-style).

Equivalent capability to the reference's gclib GFastaDb/GFastaIndex/GFaSeqGet
usage (pafreport.cpp:255,346): open a FASTA file, fetch whole records by id
without re-scanning the file.  The index is built in one streaming pass and
records byte offsets, so fetches are O(record size) seeks.

Like gclib's GFastaIndex (the ``.fai`` files pafreport rides), the index
persists: after a scan of a uniformly-wrapped FASTA a samtools-compatible
5-column ``<path>.fai`` sidecar is written, and later opens load it instead
of re-scanning — the sidecar is ignored when older than the FASTA.
Irregularly-wrapped files (which the 5-column format cannot describe) are
simply re-scanned each open.

Also provides in-memory helpers used by tests and the MSA writers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from pwasm_tpu.core.errors import PwasmError


@dataclass
class _FaiEntry:
    name: str
    length: int  # number of sequence bytes (newlines excluded)
    offset: int  # byte offset of first sequence byte
    end: int     # byte offset one past the last sequence line


class FastaFile:
    """Indexed FASTA access by sequence id.

    >>> fa = FastaFile(path)
    >>> fa.fetch("gene1")      # -> bytes (no newlines), or None if absent
    >>> len(fa)                # number of records
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        self._index: dict[str, _FaiEntry] = {}
        self._order: list[str] = []
        # per-record (linebases, linewidth, uniform) from the native
        # scan, so _write_fai needs no second pass over the file
        self._geom: dict[str, tuple[int, int, int]] = {}
        if not self._load_fai():
            self._full_scan()
            self._write_fai()

    @property
    def _fai_path(self) -> str:
        return self.path + ".fai"

    def _load_fai(self) -> bool:
        """Load the ``.fai`` sidecar when present and not older than the
        FASTA itself.  The 5-column samtools layout is name, length,
        offset, linebases, linewidth; the fetch window's end offset is
        derived from the line geometry.

        mtime alone cannot catch an mtime-preserving content swap
        (``cp -p``/``rsync -a``), so the loaded geometry is probed
        against the file's structure: a header must end right before
        each record's first base, the next record's ``>`` must sit
        exactly where the previous record's window closes, and the last
        window must close at EOF (modulo a missing final newline).  Any
        probe failure falls back to a full scan."""
        try:
            if (os.path.getmtime(self._fai_path)
                    < os.path.getmtime(self.path)):
                return False
            rows = []
            with open(self._fai_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    name, length, offset, lb, lw = line.split("\t")
                    length, offset = int(length), int(offset)
                    lb, lw = int(lb), int(lw)
                    if length < 0 or offset < 0 or lb < 1 or lw <= lb:
                        return False
                    nlines = (length + lb - 1) // lb
                    end = offset + length + nlines * (lw - lb)
                    rows.append((name, length, offset, end, lw - lb))
            if not rows:
                return False
            fsize = os.path.getsize(self.path)
            with open(self.path, "rb") as f:
                if f.read(1) != b">":
                    return False
                prev_end = 0
                for name, _l, offset, end, term in sorted(
                        rows, key=lambda r: r[2]):
                    f.seek(offset - 1)
                    if f.read(1) != b"\n":
                        return False
                    f.seek(end)
                    nxt = f.read(1)
                    if nxt != b">" and not (
                            nxt == b"" and end in (fsize, fsize + term)):
                        return False
                    # the header between the previous window and this
                    # record must still carry this record's name (a
                    # same-geometry swap with renamed records would
                    # otherwise serve stale attributions)
                    f.seek(prev_end)
                    header = f.read(min(offset - prev_end, 1 << 16))
                    if not header.startswith(b">"):
                        return False
                    tok = header[1:].split(None, 1)
                    got = tok[0] if tok else b""
                    if got.decode("utf-8", "replace") != name:
                        return False
                    prev_end = end
            for name, length, offset, end, _t in rows:
                self._add(name, length, offset, end)
        except (OSError, ValueError):
            self._index.clear()
            self._order.clear()
            return False
        return bool(self._index)

    def _write_fai(self) -> None:
        """Persist the index when every record is uniformly wrapped (the
        only shape the 5-column format can describe — foreign faidx
        readers like samtools/pysam derive in-record offsets from the
        line geometry, so a coincidental total-window match is not
        enough); best-effort — a read-only directory just skips
        persistence.  Geometry comes from the native scan when it ran
        (``self._geom``, no extra IO); the Python-scan fallback verifies
        line-by-line, one extra sequential pass."""
        rows = []
        try:
            fsize = os.path.getsize(self.path)
            with open(self.path, "rb") as f:
                for name in self._order:
                    ent = self._index[name]
                    if "\t" in name or "\n" in name:
                        return
                    geom = self._geom.get(name)
                    if geom is not None:
                        lb, lw, uniform = geom
                        if not uniform or lb < 1 or lw <= lb:
                            return
                    else:
                        # no native geometry: verify EVERY line — each
                        # full line exactly lb bases + the same
                        # terminator, no interior whitespace; the final
                        # line may be short, and may lack its
                        # terminator only at EOF
                        f.seek(ent.offset)
                        first = f.readline()
                        lb = len(first.rstrip(b"\r\n"))
                        lw = len(first)
                        if lb < 1 or lw <= lb:
                            return
                        f.seek(ent.offset)
                        left = ent.length
                        pos = ent.offset
                        while left > 0:
                            line = f.readline()
                            pos += len(line)
                            body = line.rstrip(b"\r\n")
                            if body.translate(
                                    None, b" \t\v\f\r\n") != body:
                                return
                            if len(body) != min(lb, left):
                                return
                            if len(line) - len(body) != lw - lb and not (
                                    len(body) == left and pos == fsize):
                                return
                            left -= len(body)
                        if pos != ent.end:
                            return
                    # belt: the derived window must reproduce the scan
                    nlines = (ent.length + lb - 1) // lb
                    span = ent.length + nlines * (lw - lb)
                    window = ent.end - ent.offset
                    if window != span and not (
                            window == span - (lw - lb)
                            and ent.end == fsize):
                        return
                    rows.append(f"{name}\t{ent.length}\t{ent.offset}"
                                f"\t{lb}\t{lw}\n")
            # atomic + durable publish (utils.fsio): a concurrent
            # reader must see either no sidecar or a complete one,
            # never a prefix — and a crash right after the rename must
            # not leave a complete rename of an unwritten file
            from pwasm_tpu.utils.fsio import write_durable_text
            write_durable_text(self._fai_path, "".join(rows))
        except OSError:
            # best-effort sidecar: write_durable_text cleans up its
            # own tmp file on failure
            return

    def _full_scan(self) -> None:
        # native streaming indexer when available (C++ one-pass scan,
        # bit-identical entries — parity enforced by tests/test_native.py)
        from pwasm_tpu.native import fasta_index
        try:
            entries = fasta_index(self.path)
        except OSError:
            entries = None  # fall through to the Python reader's error
        if entries is not None:
            for name, seqlen, start, end, lb, lw, uniform in entries:
                if name not in self._index:
                    self._geom[name] = (lb, lw, uniform)
                self._add(name, seqlen, start, end)
            if not self._index:
                raise PwasmError(f"Error: invalid FASTA file {self.path} !")
            return
        name = None
        seqlen = 0
        seq_start = 0
        pos = 0
        with open(self.path, "rb") as f:
            for line in f:
                linelen = len(line)
                if line.startswith(b">"):
                    if name is not None:
                        self._add(name, seqlen, seq_start, pos)
                    header = line[1:].strip()
                    name = header.split(None, 1)[0].decode() if header else ""
                    seqlen = 0
                    seq_start = pos + linelen
                elif name is not None:
                    # count exactly the bytes fetch() will return (all
                    # whitespace removed, not just line ends)
                    seqlen += len(line.translate(None, b" \t\r\n\v\f"))
                pos += linelen
            if name is not None:
                self._add(name, seqlen, seq_start, pos)
        if not self._index:
            raise PwasmError(f"Error: invalid FASTA file {self.path} !")

    def _add(self, name: str, seqlen: int, start: int, end: int) -> None:
        if name not in self._index:
            self._index[name] = _FaiEntry(name, seqlen, start, end)
            self._order.append(name)

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    @property
    def names(self) -> list[str]:
        return list(self._order)

    def length(self, name: str) -> int:
        return self._index[name].length

    def fetch(self, name: str) -> bytes | None:
        """Fetch a full record's sequence (newlines stripped), or None."""
        ent = self._index.get(name)
        if ent is None:
            return None
        from pwasm_tpu.native import fasta_fetch
        try:
            raw_n = fasta_fetch(self.path, ent.offset, ent.end)
        except OSError:
            raw_n = None
        if raw_n is not None:
            return raw_n
        with open(self.path, "rb") as f:
            f.seek(ent.offset)
            raw = f.read(ent.end - ent.offset)
        # strip ALL whitespace, matching the per-line strip() used when
        # indexing — otherwise length() and fetch() disagree on files with
        # trailing blanks and stray bytes later encode as phantom Ns
        return bytes(raw.translate(None, b" \t\r\n\v\f"))

    def file_size(self) -> int:
        """Size of the FASTA file in bytes.

        The reference auto-selects full-genome mode when this exceeds 120000
        bytes (pafreport.cpp:253-262, quirk SURVEY.md §2.5.7) — by *file
        size*, not sequence length; we preserve that contract.
        """
        return os.path.getsize(self.path)


def write_fasta(path: str, records: list[tuple[str, bytes]], width: int = 60) -> None:
    """Write records as FASTA with the given line width (test helper)."""
    with open(path, "w") as f:
        for name, seq in records:
            f.write(f">{name}\n")
            s = seq.decode() if isinstance(seq, (bytes, bytearray)) else seq
            for i in range(0, len(s), width):
                f.write(s[i:i + width] + "\n")
