"""DNA alphabet tables: complement, integer encoding, codon translation.

Covers the surface the reference pulls from gclib's ``gdna`` (IUPAC
complement tables used by ``revCompl``, pafreport.cpp:469-472) and ``codons``
(``translateCodon``, pafreport.cpp:824-825,855).  Device-side kernels use the
integer encodings and LUTs defined here; host-side string code uses the byte
translation tables.

Base codes (device layout): A=0 C=1 G=2 T=3 N=4, gap=5.  The 0..3 range is
what the 2-bit packers and the banded-DP kernel consume; code 4 captures any
ambiguity character; code 5 is the explicit gap bucket used by the consensus
pileup (mirrors the 6-bucket column counts of GAlnColumn, GapAssem.h:257-264).
"""

from __future__ import annotations

import numpy as np

CODE_A = 0
CODE_C = 1
CODE_G = 2
CODE_T = 3
CODE_N = 4
CODE_GAP = 5

BASE_CHARS = b"ACGTN-"

# ---------------------------------------------------------------------------
# IUPAC complement (case preserving), equivalent to GStr::tr(IUPAC_DEFS,
# IUPAC_COMP) followed by reverse() in the reference's revCompl().
# ---------------------------------------------------------------------------
_IUPAC_PAIRS = {
    "A": "T", "C": "G", "G": "C", "T": "A", "U": "A",
    "M": "K", "R": "Y", "W": "W", "S": "S", "Y": "R", "K": "M",
    "V": "B", "H": "D", "D": "H", "B": "V", "N": "N", "X": "X",
}


def _build_comp_table() -> bytes:
    tbl = bytearray(range(256))
    for a, b in _IUPAC_PAIRS.items():
        tbl[ord(a)] = ord(b)
        tbl[ord(a.lower())] = ord(b.lower())
    return bytes(tbl)


COMP_TABLE = _build_comp_table()


def complement(seq: bytes) -> bytes:
    """IUPAC complement, preserving case, without reversing."""
    return seq.translate(COMP_TABLE)


def revcomp(seq: bytes) -> bytes:
    """Reverse complement, preserving case (reference: revCompl,
    pafreport.cpp:469-472)."""
    return seq.translate(COMP_TABLE)[::-1]


# ---------------------------------------------------------------------------
# Byte -> integer code encoding (and back)
# ---------------------------------------------------------------------------
def _build_encode_table() -> np.ndarray:
    tbl = np.full(256, CODE_N, dtype=np.int8)
    for ch, code in ((b"A", CODE_A), (b"C", CODE_C), (b"G", CODE_G),
                     (b"T", CODE_T), (b"U", CODE_T)):
        tbl[ch[0]] = code
        tbl[ch.lower()[0]] = code
    tbl[ord("-")] = CODE_GAP
    tbl[ord("*")] = CODE_GAP  # ACE-style gap char (GASeq::printGappedFasta)
    return tbl


ENCODE_TABLE = _build_encode_table()
DECODE_TABLE = np.frombuffer(BASE_CHARS, dtype=np.uint8)


def encode(seq: bytes) -> np.ndarray:
    """Encode a byte string to int8 base codes (A0 C1 G2 T3 N4 gap5)."""
    arr = np.frombuffer(bytes(seq), dtype=np.uint8)
    return ENCODE_TABLE[arr]


def decode(codes: np.ndarray) -> bytes:
    """Decode int8 base codes back to an upper-case byte string."""
    return DECODE_TABLE[np.asarray(codes, dtype=np.int64)].tobytes()


# ---------------------------------------------------------------------------
# Codon translation (standard genetic code; stop='.', ambiguous/short='X').
# Matches the behavior of gclib's translateCodon as used by predictImpact
# (pafreport.cpp:824-825,855): reading off the end of the sequence or through
# a non-ACGT base yields 'X'.
# ---------------------------------------------------------------------------
_CODON_TABLE = {
    "TTT": "F", "TTC": "F", "TTA": "L", "TTG": "L",
    "CTT": "L", "CTC": "L", "CTA": "L", "CTG": "L",
    "ATT": "I", "ATC": "I", "ATA": "I", "ATG": "M",
    "GTT": "V", "GTC": "V", "GTA": "V", "GTG": "V",
    "TCT": "S", "TCC": "S", "TCA": "S", "TCG": "S",
    "CCT": "P", "CCC": "P", "CCA": "P", "CCG": "P",
    "ACT": "T", "ACC": "T", "ACA": "T", "ACG": "T",
    "GCT": "A", "GCC": "A", "GCA": "A", "GCG": "A",
    "TAT": "Y", "TAC": "Y", "TAA": ".", "TAG": ".",
    "CAT": "H", "CAC": "H", "CAA": "Q", "CAG": "Q",
    "AAT": "N", "AAC": "N", "AAA": "K", "AAG": "K",
    "GAT": "D", "GAC": "D", "GAA": "E", "GAG": "E",
    "TGT": "C", "TGC": "C", "TGA": ".", "TGG": "W",
    "CGT": "R", "CGC": "R", "CGA": "R", "CGG": "R",
    "AGT": "S", "AGC": "S", "AGA": "R", "AGG": "R",
    "GGT": "G", "GGC": "G", "GGA": "G", "GGG": "G",
}


_CODON_TABLE_B = {k.encode(): v for k, v in _CODON_TABLE.items()}


def translate_codon(seq: bytes, pos: int = 0) -> str:
    """Translate the codon starting at ``pos``; 'X' if short or ambiguous."""
    codon = bytes(seq[pos:pos + 3])
    aa = _CODON_TABLE_B.get(codon)     # fast path: already upper ACGT
    if aa is not None:
        return aa
    codon = codon.upper().replace(b"U", b"T")
    if len(codon) < 3:
        return "X"
    return _CODON_TABLE_B.get(codon, "X")


def _build_aa_lut() -> np.ndarray:
    """5**3 LUT over base codes (A0..T3, N4) -> amino-acid ASCII (uint8).

    Any codon containing code 4 (N) maps to 'X'; stop codons map to '.'.
    Device kernels index this with ``c0*25 + c1*5 + c2``.
    """
    lut = np.full(125, ord("X"), dtype=np.uint8)
    bases = "ACGT"
    for i0, b0 in enumerate(bases):
        for i1, b1 in enumerate(bases):
            for i2, b2 in enumerate(bases):
                aa = _CODON_TABLE[b0 + b1 + b2]
                lut[i0 * 25 + i1 * 5 + i2] = ord(aa)
    return lut


AA_LUT = _build_aa_lut()


def translate_codes(codes: np.ndarray) -> np.ndarray:
    """Vectorized translation of an (..., 3k) base-code array to amino-acid
    ASCII codes of shape (..., k).  Positions beyond the array or ambiguous
    codons yield 'X'."""
    codes = np.asarray(codes)
    n_codons = codes.shape[-1] // 3
    trimmed = np.clip(codes[..., : n_codons * 3], 0, CODE_N)
    c = trimmed.reshape(*codes.shape[:-1], n_codons, 3).astype(np.int64)
    idx = c[..., 0] * 25 + c[..., 1] * 5 + c[..., 2]
    return AA_LUT[idx]
