"""Batched report byte assembly (jax-free).

The scalar emit path paid ~4-5 Python calls per event row — an
``analyzed[id(di)]`` dict probe, a tuple unpack, ``format_event_row``
(three per-field ``bytes.decode`` round-trips plus two f-string
interpolations), and ``Summary.add_event`` (half a dozen dict
operations) — and that per-event constant was the realistic-scale
host wall's largest flat term (BASELINE.md ceiling analysis).  This
module assembles one whole report block per flush instead:

- one fused pass over the batch builds every row with the truncation
  rules inlined and NO intermediate per-field objects;
- the ``-s`` summary counters accumulate in local integers during the
  same pass and fold into the ``Summary`` once per batch
  (:meth:`~pwasm_tpu.report.diff_report.Summary.fold_event_counts`);
- the assembled rows land in a REUSED list (:class:`FormatBuffers`,
  thread-local) so neither the per-flush list growth nor the warm-serve
  daemon's per-job allocation spike recurs — persistent worker threads
  (the CLI's host pipeline, the daemon's job workers) keep their
  scratch across batches and across jobs;
- the block leaves as ONE ``str`` for a single ``f.write`` per batch.

Byte-parity contract: every row is byte-for-byte what
``diff_report.format_event_row`` / ``format_header`` produce — the
assembly works in ``str`` space because the report stream is a
text-mode file and Python's ascii ``decode(..., "replace")`` is
byte-wise, so field-at-a-time and block-at-a-time conversions agree.
``PWASM_HOST_FORMAT=0`` routes ``emit_batch_rows`` back to the scalar
per-row loop (mirroring ``PWASM_HOST_COLUMNAR=0``) so a formatting
regression is bisectable in production.
"""

from __future__ import annotations

import os
import threading

from pwasm_tpu.report.diff_report import (MAX_EVLEN, Summary,
                                          format_header)

_TCTX_MAX = 10 + MAX_EVLEN      # target-context truncation threshold


def vector_format_enabled() -> bool:
    """The A/B escape hatch: ``PWASM_HOST_FORMAT=0`` falls back to the
    scalar ``format_event_row`` emit loop (read per flush, like
    ``PWASM_HOST_COLUMNAR``)."""
    return os.environ.get("PWASM_HOST_FORMAT", "1") != "0"


class FormatBuffers:
    """Reusable row-assembly scratch.  A Python list's backing store
    grows amortized — reusing one pre-grown list per thread means a
    steady-state flush (or a warm-serve job) performs zero list
    reallocations.  Only the list OBJECT persists; the row strings and
    the joined block are transient per batch."""

    __slots__ = ("rows", "batches")

    def __init__(self) -> None:
        self.rows: list[str] = []
        self.batches = 0        # batches formatted through this scratch
        #                         (observability for the reuse tests)


_TL = threading.local()


def get_buffers() -> FormatBuffers:
    """The calling thread's persistent :class:`FormatBuffers` (created
    on first use; the serve daemon's worker threads and the CLI's host
    pipeline worker are long-lived, so this is cross-batch AND
    cross-job reuse)."""
    buf = getattr(_TL, "buffers", None)
    if buf is None:
        buf = _TL.buffers = FormatBuffers()
    return buf


def format_batch_block(batch, analyzed: dict,
                       summary: Summary | None) -> str:
    """Assemble one report batch — headers interleaved with event rows,
    exactly the bytes the scalar ``print_diff_info`` loop writes — as a
    single ``str``; fold the batch's summary counters in bulk.

    ``batch`` is the CLI's flush list of ``(aln, rlabel, tlabel,
    refseq)``; ``analyzed`` maps ``id(di)`` to the analysis tuple
    ``(aa, aapos, rctx, status, impact)`` (the ``analyze_event_host``
    contract, produced by the columnar engine or the device fetch).
    """
    buf = get_buffers()
    rows = buf.rows
    rows.clear()
    buf.batches += 1
    append = rows.append
    # summary counters: locals in the hot loop, folded once at the end
    n_s = n_i = n_d = 0          # events per type
    b_s = b_i = b_d = 0          # bases per type
    c_hp = c_mo = c_un = 0       # cause classes
    i_syn = i_non = i_stop = i_fs = 0   # impact classes
    count = summary is not None
    for aln, rlabel, tlabel, _refseq in batch:
        append(format_header(aln, rlabel, tlabel))
        if count:
            summary.add_alignment(aln)
        for di in aln.tdiffs:
            aa, aapos, rctx, status, impact = analyzed[id(di)]
            evt = di.evt
            evtbases = di.evtbases
            if len(evtbases) > MAX_EVLEN:
                eb = f"[{len(evtbases)}]"
            else:
                eb = evtbases.decode("ascii", "replace")
            if evt == "S":
                evtsub = di.evtsub
                if len(evtsub) > MAX_EVLEN:
                    mid = f"[{len(evtsub)}]:{eb}"
                else:
                    mid = f"{evtsub.decode('ascii', 'replace')}:{eb}"
            elif evt == "I":
                mid = f":{eb}"
            else:
                mid = f"{eb}:"
            tctx = di.tctx
            if len(tctx) > _TCTX_MAX:
                tctx_s = (f"{tctx[:5].decode('ascii', 'replace')}"
                          f"[{len(tctx) - 10}]"
                          f"{tctx[-5:].decode('ascii', 'replace')}")
            else:
                tctx_s = tctx.decode("ascii", "replace")
            append(f"{evt}\t{di.rloc + 1}\t{aapos}({aa})\t{mid}\t"
                   f"{di.tloc + 1}\t{tctx_s}\t"
                   f"{rctx.decode('ascii', 'replace')}\t{status}\t"
                   f"{impact}\n")
            if count:
                if evt == "S":
                    n_s += 1
                    b_s += len(evtbases)
                elif evt == "I":
                    n_i += 1
                    b_i += len(evtbases)
                else:
                    n_d += 1
                    b_d += di.evtlen
                if status == "homopolymer":
                    c_hp += 1
                elif status.startswith("motif"):
                    c_mo += 1
                else:
                    c_un += 1
                if impact:
                    if impact == "synonymous":
                        i_syn += 1
                    elif "premature stop" in impact:
                        i_stop += 1
                    elif impact.startswith("frame shift"):
                        i_fs += 1
                    else:
                        i_non += 1
    if count:
        summary.fold_event_counts(
            {"S": n_s, "I": n_i, "D": n_d},
            {"S": b_s, "I": b_i, "D": b_d},
            {"homopolymer": c_hp, "motif": c_mo, "unknown": c_un},
            {"synonymous": i_syn, "nonsynonymous": i_non,
             "premature_stop": i_stop, "frame_shift": i_fs})
    block = "".join(rows)
    rows.clear()    # drop the row strings, keep the grown list object
    return block
