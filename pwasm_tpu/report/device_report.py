"""Device-path diff analysis: batch the events of many alignments through
the fused ctx_scan program, then assemble the same report rows as the
scalar path (tested byte-identical).

Division of labor: the device computes homopolymer/motif attribution and
the codon-impact amino acids over the whole event batch in one XLA
program; the host slices the 9bp context strings (O(9) per event, and
byte-faithful for IUPAC ambiguity characters that the int8 code space
collapses to N) and formats rows with the shared formatter.

Dispatch budget (VERDICT r5 item 3): through a tunnel every host<->device
round-trip costs ~1-2 ms, so the flush path is transfer-lean by design —
events ship as two stacked tensors, the reference pads to a power-of-two
bucket (one compiled program per bucket, not per ref length), and the
whole analysis returns as ONE packed int32 fetch
(``ctx_scan_packed``/``unpack_ctx_scan``) instead of ~16 per-field
round-trips.  Every launch/fetch is counted on ``RunStats``
(``device_dispatches``/``device_flushes``) and gated at realistic scale
by tests/test_realistic_scale.py.

Scope limits (callers fall back to the scalar path per event when hit):
- events longer than ``max_ev`` bases;
- references longer than ``max_len - max_ev`` (the frameshift stop-scan
  window must cover the whole modified suffix).
"""

from __future__ import annotations

import sys

import numpy as np

from pwasm_tpu.core.config import DEFAULT_MOTIFS
from pwasm_tpu.core.dna import encode
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.ops.ctx_scan import (PAD as PAD_CODE, ctx_scan_packed,
                                    next_pow2, pack_events,
                                    pack_motifs, ref_bucket_len,
                                    unpack_ctx_scan)
from pwasm_tpu.report.columnar import assemble_results, emit_batch_rows
from pwasm_tpu.report.diff_report import get_ref_context  # noqa: F401

MAX_EV = 16
_warned_fallback = False


def _pad_axis0(v, n: int):
    """Pad an event tensor's leading axis to a multiple of ``n`` (rows
    of zeros/PAD are inert 0-length events, like pack_events' own
    bucket padding)."""
    import jax.numpy as jnp

    pad = -v.shape[0] % n
    if not pad:
        return v
    fill = PAD_CODE if v.dtype == jnp.int8 else 0
    return jnp.pad(v, ((0, pad),) + ((0, 0),) * (v.ndim - 1),
                   constant_values=fill)


def submit_events_device(refseq: bytes, events,
                         skip_codan: bool = False,
                         motifs=DEFAULT_MOTIFS, max_ev: int = MAX_EV,
                         mesh=None, stats=None, supervisor=None):
    """Launch the device analysis of a batch of DiffEvents and return a
    ``finish() -> list[tuple]`` closure that fetches and assembles the
    results.

    JAX dispatch is asynchronous, so between ``submit`` and ``finish``
    the device computes while the host does other work — the CLI keeps a
    two-deep in-flight pipeline, so batch k's device program overlaps the
    host formatting of batches k-1/k-2, hiding the transfer/launch
    latency.  Events over ``max_ev`` bases take the scalar path inside
    finish().

    ``supervisor`` (resilience.BatchSupervisor) supervises the device
    round-trip: the fetched outputs are guardrail-validated, a failed
    or rejected fetch RE-SUBMITS the whole program (bounded retries
    with backoff), and exhaustion raises for the caller's scalar-path
    degradation.  The happy path keeps the submit/finish overlap —
    only retries lose it.
    """
    import jax.numpy as jnp

    from pwasm_tpu.report.diff_report import analyze_event_host

    if not events:
        return lambda: []
    ref_len = len(refseq)
    max_codons = max_ev // 3 + 2
    # pad the reference to a power-of-two bucket so the jitted program
    # is keyed on the bucket, not the exact ref length — a handful of
    # compiled programs serve every flush and every reference;
    # positions >= ref_len hold PAD, which never matches a base and is
    # masked by ref_len elsewhere
    max_len = ref_bucket_len(ref_len, max_ev)
    fits = [len(ev.evtbases) <= max_ev and len(ev.evtsub) <= max_ev
            for ev in events]
    small = [ev for ev, ok in zip(events, fits) if ok]
    big = [ev for ev, ok in zip(events, fits) if not ok]
    out = None
    chunks: list[list] = []
    pre: list = []
    if small:
        mot_codes, mot_lens = pack_motifs(motifs)
        ref_codes = np.full(max_len, PAD_CODE, dtype=np.int8)
        ref_codes[:ref_len] = encode(refseq.upper())

        def launch_for(evs):
            packed = pack_events(evs, max_ev)
            if mesh is not None:
                # --shard: spread the event batch over the mesh (all
                # axes flattened — the analysis is embarrassingly
                # parallel, so GSPMD partitions the fused program with
                # no collectives)
                import jax
                from jax.sharding import NamedSharding, PartitionSpec

                from pwasm_tpu.parallel.bucketing import mesh_multiple
                n_mesh = mesh_multiple(mesh)
                packed = {
                    k: jax.device_put(
                        _pad_axis0(v, n_mesh),
                        NamedSharding(mesh, PartitionSpec(
                            tuple(mesh.axis_names),
                            *([None] * (v.ndim - 1)))))
                    for k, v in packed.items()}
            return ctx_scan_packed(jnp.asarray(ref_codes),
                                   jnp.int32(ref_len), packed, mot_codes,
                                   mot_lens, max_codons=max_codons,
                                   max_len=max_len,
                                   skip_codan=skip_codan)

        def note_pad(evs) -> None:
            # pow2 pad-waste accounting (ISSUE 11): pack_events pads
            # the event axis to next_pow2(E, 256) — record live rows
            # vs launched slots so pwasm_device_pad_waste_ratio can
            # say how much of the device batch was bucket padding
            if stats is not None and hasattr(stats, "note_pad"):
                stats.note_pad(len(evs), next_pow2(len(evs)))

        if supervisor is None:
            note_pad(small)
            out = launch_for(small)
        else:
            # a prior OOM demoted the run's pow2 batch ceiling: pre-
            # chunk this flush to it so the allocation that failed is
            # never launched again (one bisection per run, not one per
            # flush); each chunk is supervised independently below
            ceil = supervisor.bucket_ceiling
            if ceil and len(small) > ceil:
                chunks = [small[i:i + ceil]
                          for i in range(0, len(small), ceil)]
            else:
                chunks = [small]
            for evs in chunks:
                note_pad(evs)
                try:
                    pre.append(launch_for(evs))  # async submit;
                except Exception:    # failures retried at finish
                    pre.append(None)  # inside the supervised attempt

    def fetch_unpack(o) -> dict:
        # ONE host fetch for the whole analysis, then numpy views
        return unpack_ctx_scan(np.asarray(o), max_codons, skip_codan)

    def merge_parts(parts) -> dict:
        """Reassemble per-part ctx_scan host dicts in item order: each
        part contributes exactly its live rows (its arrays are padded
        to a compile bucket, so slice before concatenating)."""
        if len(parts) == 1:
            return parts[0][1]
        keys = list(parts[0][1].keys())
        return {k: np.concatenate(
            [np.asarray(r[k])[:len(evs)] for evs, r in parts], axis=0)
            for k in keys}

    def finish() -> list[tuple]:
        results: dict[int, tuple] = {}
        if small:
            if supervisor is not None:
                from pwasm_tpu.resilience.guardrails import check_ctx_scan
                from pwasm_tpu.resilience.supervisor import \
                    BisectableBatch

                def validate_for(h, evs):
                    check_ctx_scan(h, len(evs), ref_len, len(motifs),
                                   skip_codan)

                parts = []
                for evs, submitted in zip(chunks, pre):
                    pending = [submitted]

                    def attempt(evs=evs, pending=pending):
                        o = pending.pop() if pending else None
                        o = launch_for(evs) if o is None else o
                        return fetch_unpack(o)

                    part = supervisor.run(
                        "ctx_scan", attempt,
                        validate=lambda h, evs=evs: validate_for(
                            h, evs),
                        bisect=BisectableBatch(
                            items=evs,
                            attempt_for=lambda e: fetch_unpack(
                                launch_for(e)),
                            combine=merge_parts,
                            validate_for=validate_for))
                    parts.append((evs, part))
                host = merge_parts(parts)
            else:
                if stats is not None \
                        and hasattr(stats, "note_dispatch"):
                    # unsupervised direct call: count the round-trip
                    # here (supervised runs count inside supervisor.run)
                    stats.note_dispatch("ctx_scan")
                    stats.note_flush()
                host = fetch_unpack(out)
            if stats is not None:
                # per-event routing observability (VERDICT r4 weak #6):
                # credited only AFTER the device fetch succeeded — a
                # failed batch is replayed on host and must count as
                # scalar there, not here
                stats.device_events += len(small)
            for ev, r in zip(small, assemble_results(
                    small, host, refseq, motifs, skip_codan)):
                results[id(ev)] = r
        if big and stats is not None:
            stats.scalar_events += len(big)
        for ev in big:
            results[id(ev)] = analyze_event_host(ev, refseq, skip_codan,
                                                 motifs)
        return [results[id(ev)] for ev in events]

    return finish


def analyze_events_device(refseq: bytes, events, skip_codan: bool = False,
                          motifs=DEFAULT_MOTIFS,
                          max_ev: int = MAX_EV) -> list[tuple]:
    """Synchronous submit+finish: a list of (aa, aapos, rctx, status,
    impact) tuples in event order — the same contract as
    ``analyze_event_host`` (and NB: like the host path it upper-cases
    each event's ``evtbases`` in place, matching printDiffInfo)."""
    return submit_events_device(refseq, events, skip_codan, motifs,
                                max_ev)()


def submit_diff_info_batch(batch, f, skip_codan: bool = False,
                           motifs=DEFAULT_MOTIFS, summary=None,
                           max_ev: int = MAX_EV, stats=None, mesh=None,
                           supervisor=None):
    """Launch the device analysis for a report batch and return a
    ``finish() -> None`` closure that fetches the results and writes the
    rows (the SURVEY.md §3.1 TPU boundary: host parse -> batch -> one
    device program -> host format — with the device program of batch k
    overlapping the host formatting of earlier batches, see the CLI).

    ``batch`` is a list of (aln: PafAlignment, rlabel, tlabel,
    refseq: bytes) in input order.  Events are grouped per distinct
    refseq (the device program is specialized on the reference tensor),
    analyzed in one ``ctx_scan`` call per group, then rows are emitted in
    exactly the order the scalar path would produce."""
    from pwasm_tpu.report.diff_report import print_diff_info

    def scalar_replay(e: Exception) -> None:
        # the batch analysis failed before any row was written; replay
        # the whole batch through the scalar path, which writes rows
        # progressively and raises at exactly the failing event — the
        # same observable behavior as --device=cpu.  Warn once so a dead
        # device path can't hide behind the always-correct replay.
        global _warned_fallback
        if stats is not None:
            stats.fallback_batches += 1
            if supervisor is not None and hasattr(stats, "res_fallbacks"):
                # the supervised pipeline degraded this batch to the
                # host: surface it in the resilience block too
                stats.res_fallbacks += 1
            # every event of this batch is (re)analyzed on host
            stats.scalar_events += sum(
                len(aln.tdiffs) for aln, _rl, _tl, _rs in batch)
        if not _warned_fallback:
            _warned_fallback = True
            from pwasm_tpu.utils import exc_detail
            print(f"Warning: device batch analysis failed "
                  f"({exc_detail(e)}); falling back to the scalar "
                  f"path for this run", file=sys.stderr)
        for aln, rlabel, tlabel, refseq in batch:
            print_diff_info(aln, rlabel, tlabel, f, refseq,
                            skip_codan=skip_codan, motifs=motifs,
                            summary=summary)

    # group event lists by refseq identity, preserving alignment order
    groups: dict[bytes, list] = {}
    for aln, _rl, _tl, refseq in batch:
        groups.setdefault(refseq, []).extend(aln.tdiffs)
    finishes = []
    try:
        for refseq, events in groups.items():
            finishes.append((events, submit_events_device(
                refseq, events, skip_codan, motifs, max_ev, mesh=mesh,
                stats=stats, supervisor=supervisor)))
    except Exception as e:
        err = e

        def finish_failed() -> None:
            scalar_replay(err)

        return finish_failed

    def finish() -> None:
        from pwasm_tpu.resilience.supervisor import ResilienceError

        analyzed: dict[int, tuple] = {}
        # snapshot the routing counters: if a later group fails after an
        # earlier one was credited, the whole batch replays on host and
        # the partial device credit must be rolled back (the replay adds
        # every event as scalar)
        snap = (stats.device_events, stats.scalar_events) \
            if stats is not None else None
        try:
            for events, fin in finishes:
                for ev, r in zip(events, fin()):
                    analyzed[id(ev)] = r
        except ResilienceError:
            # --fallback=fail: the policy forbids the scalar-path
            # degradation below — abort the run instead
            raise
        except Exception as e:
            if stats is not None:
                stats.device_events, stats.scalar_events = snap
            scalar_replay(e)
            return
        emit_batch_rows(batch, analyzed, f, summary)

    return finish


def print_diff_info_batch(batch, f, skip_codan: bool = False,
                          motifs=DEFAULT_MOTIFS, summary=None,
                          max_ev: int = MAX_EV) -> None:
    """Synchronous submit+finish of one report batch."""
    submit_diff_info_batch(batch, f, skip_codan, motifs, summary,
                           max_ev)()


# (predictImpact text assembly lives in report/columnar.py
# ``_impact_text_l``, shared by the device finish path and the host
# columnar engine through ``assemble_results``.)
