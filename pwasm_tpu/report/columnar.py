"""Vectorized host event analysis + row assembly (jax-free).

The host scalar path's per-event loop (``diff_report.analyze_event_host``
— context window, homopolymer/motif attribution, codon impact) was the
realistic-scale CLI's hot spot (VERDICT r5 item 8: report formatting and
event assembly).  This module runs the SAME formulas as the device
program — literally the same functions, ``ops/ctx_scan_impl.py`` under
the numpy namespace — over a whole batch of alignments' events at once,
then assembles rows with one writer call per batch.

Byte-exactness contract: ``diff_report`` stays the scalar ground truth.
Any event the columnar formulas cannot reproduce byte-for-byte is
ROUTED to the scalar analyzer instead of approximated:

- events longer than ``HOST_MAX_EV`` bases (the fixed-shape tensors cap
  event width, like the device path's MAX_EV scope limit);
- events carrying non-ACGT bases (the int8 code space collapses IUPAC
  codes to N, so code-space compares could diverge from the scalar
  path's byte compares — e.g. hpolyCheck on an 'RRRR' run);
- when the reference itself holds non-ACGT bases, events whose 9bp
  window touches them (same code-space concern for the motif scan);
- flagged substitution mismatches (the reference's fatal
  modseq-vs-evtsub verification): re-run through the scalar path so
  the error message, indices and raise point are byte-identical.

The frameshift stop scan is windowed on host: the device's dense
whole-suffix scan is right for a TPU but O(ref_len) per event on a CPU,
while the scalar reference usually stops within a few codons.  The
first pass scans a short window; the rare lanes with no stop inside it
re-scan with the full suffix — results are identical by construction
(the window only bounds how far the SAME formula looks).
"""

from __future__ import annotations

import numpy as np

from pwasm_tpu.core.config import DEFAULT_MOTIFS
from pwasm_tpu.core.dna import CODE_N, encode
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.ops.ctx_scan_impl import (EVT_S, PAD, indel_stop_scan,
                                         pack_events_np, pack_motifs_np,
                                         sub_impact)
from pwasm_tpu.ops import ctx_scan_impl as _impl
from pwasm_tpu.report.diff_report import (Summary, analyze_event_host,
                                          format_event_row, format_header,
                                          get_ref_context, print_diff_info)

HOST_MAX_EV = 64       # events wider than this take the scalar path
_STOP_WINDOW = 258     # first-pass stop-scan window (86 codons: the
#                        expected stop arrives within ~21 codons on
#                        random sequence, so ~98% of lanes resolve here)


def host_ctx_scan(ref: np.ndarray, ref_len: int, ev: dict,
                  mot_codes: np.ndarray, mot_lens: np.ndarray,
                  max_codons: int, skip_codan: bool) -> dict:
    """Numpy twin of ``ops/ctx_scan.ctx_scan`` over live (unpadded)
    events — same formulas via ``ctx_scan_impl``, but lane-filtered the
    way a CPU wants it: substitution impact only on S lanes, the stop
    scan only on I/D lanes and windowed with escalation."""
    rloc = ev["rloc"]
    E = rloc.shape[0]
    out, r_trloc = _impl.ctx_scan_prologue(ref, ref_len, ev, mot_codes,
                                           mot_lens)
    if skip_codan:
        return out
    K = max_codons
    s_idx = np.nonzero(ev["evt"] == EVT_S)[0]
    if s_idx.size:
        # right-size the codon window to this batch's widest live
        # substitution (identical results: codons past a sub's own
        # span are invalid either way) — K tracks max_ev but real subs
        # span 1-3 codons, so the dense (E, K) planes shrink ~8x
        e_off = rloc[s_idx] - r_trloc[s_idx]
        span = (e_off + np.maximum(ev["nbases"][s_idx], 1) - 1) // 3 \
            - e_off // 3 + 1
        K = min(K, int(span.max()))
    out.update(
        s_orig_aa=np.zeros((E, K), np.uint8),
        s_new_aa=np.zeros((E, K), np.uint8),
        s_aapos=np.zeros((E, K), np.int64),
        s_valid=np.zeros((E, K), bool),
        s_mismatch=np.zeros(E, bool),
        stop_aapos=np.full(E, -1, np.int32),
        aa4=np.zeros((E, 4), np.uint8), maa4=np.zeros((E, 4), np.uint8),
        aa4_valid=np.zeros((E, 4), bool),
        maa4_valid=np.zeros((E, 4), bool))
    if s_idx.size:
        so, sn, sp, sv, sm = sub_impact(
            ref, rloc[s_idx], ev["nbases"][s_idx],
            ev["evtbases"][s_idx], ev["evtsub"][s_idx], r_trloc[s_idx],
            K)
        out["s_orig_aa"][s_idx] = so
        out["s_new_aa"][s_idx] = sn
        out["s_aapos"][s_idx] = sp
        out["s_valid"][s_idx] = sv
        out["s_mismatch"][s_idx] = sm
    sel = np.nonzero(ev["evt"] != EVT_S)[0]
    window = _STOP_WINDOW
    while sel.size:
        stop, aa4, maa4, a4v, m4v = indel_stop_scan(
            ref, ref_len, rloc[sel], ev["evt"][sel], ev["evtlen"][sel],
            ev["nbases"][sel], ev["evtbases"][sel], r_trloc[sel],
            window)
        out["stop_aapos"][sel] = stop
        out["aa4"][sel] = aa4
        out["maa4"][sel] = maa4
        out["aa4_valid"][sel] = a4v
        out["maa4_valid"][sel] = m4v
        # lanes with no stop inside the window whose modified suffix
        # extends past it re-scan with the full suffix (identical
        # formula, wider look) — the aa4/maa4 fields are already final
        # (codons 1..4 sit inside any window, and a stop past codon 4
        # gates them exactly like no stop at all)
        is_ins = ev["evt"][sel] == _impl.EVT_I
        nb = np.where(is_ins, ev["nbases"][sel], ev["evtlen"][sel])
        modlen = np.where(is_ins, ref_len - r_trloc[sel] + nb,
                          ref_len - r_trloc[sel] - nb)
        scanned = 3 * (window // 3) + 2   # first unscanned codon's end
        unresolved = (stop < 0) & (scanned < modlen)
        sel = sel[unresolved]
        if window >= int(ref_len) + HOST_MAX_EV + 3:
            break
        window = int(ref_len) + HOST_MAX_EV + 3
    return out


_SCALAR_FIELDS = ("aa", "aapos", "hpoly", "motif", "s_mismatch",
                  "stop_aapos")


def _impact_text_l(ev, k: int, L: dict, strict_subs: bool,
                   refseq: bytes, skip_codan: bool, motifs) -> str:
    """predictImpact's text from analysis results (pafreport.cpp:804-883
    semantics), all fields from the bulk-converted lists ``L`` — the
    per-codon planes are converted ONCE per batch in
    :func:`assemble_results` (the former per-row ``.tolist()``
    extraction cost 4-8 numpy calls per indel event).  With
    ``strict_subs`` a flagged substitution mismatch re-runs the event
    through the scalar analyzer so message/indices match the scalar
    ground truth byte-for-byte; without it the device path's generic
    message is raised."""
    if ev.evt == "S":
        if L["s_mismatch"][k]:
            if strict_subs:
                # scalar replay raises the reference's exact error (or,
                # if the byte-level check disagrees with the code-level
                # flag, yields the scalar ground-truth result)
                return analyze_event_host(ev, refseq, skip_codan,
                                          motifs)[4]
            raise PwasmError(
                "Error: modseq not matching di.evtsub !\n")
        if L["s_syn"][k]:
            # vectorized fast path: no valid codon changed — the
            # per-codon row walk below would emit no parts
            return "synonymous"
        parts = []
        s_valid = L["s_valid"][k]
        s_orig = L["s_orig_aa"][k]
        s_new = L["s_new_aa"][k]
        s_pos = None
        for d in range(len(s_orig)):
            if not s_valid[d]:
                break
            aa = chr(s_orig[d])
            maa = chr(s_new[d])
            if aa != maa:
                if s_pos is None:
                    s_pos = L["s_aapos"][k]
                aapos = s_pos[d]
                s = f"AA{aapos}|{aa}:{maa}"
                if maa == ".":
                    s += f"|premature stop at AA{aapos}"
                parts.append(s)
        return ", ".join(parts) if parts else "synonymous"
    stop = L["stop_aapos"][k]
    if stop >= 0:
        return f"premature stop at AA{stop}"
    aa4 = "".join(chr(c) for c, v in
                  zip(L["aa4"][k], L["aa4_valid"][k]) if v)
    maa4 = "".join(chr(c) for c, v in
                   zip(L["maa4"][k], L["maa4_valid"][k]) if v)
    if aa4 and maa4:
        return f"frame shift {aa4}+:{maa4}+"
    return ""


def assemble_results(events, host: dict, refseq: bytes, motifs,
                     skip_codan: bool, defer=None,
                     strict_subs: bool = False) -> list:
    """Per-event ``(aa, aapos, rctx, status, impact)`` tuples — the
    ``analyze_event_host`` contract — from an analysis dict (a device
    fetch or ``host_ctx_scan`` output).  Upper-cases each event's
    ``evtbases`` in place, matching printDiffInfo.  ``defer[k]`` routes
    event ``k`` wholesale through the scalar analyzer (the columnar
    path's byte-exactness escape hatch)."""
    # bulk tolist for the per-event scalars (python-int indexing from
    # lists is ~5x cheaper than numpy scalar extraction at report
    # scale); the (E, K) codon planes stay arrays and convert per ROW
    # on demand — most of their content is never read
    A = {k: np.asarray(v) for k, v in host.items()
         if k not in ("rctx", "rctxloc")}
    L = {k: A[k].tolist() for k in _SCALAR_FIELDS if k in A}
    if "s_valid" in A:
        # synonymous = no valid codon changed (computed vectorized so
        # the common case skips the per-codon row walk entirely)
        changed = (A["s_orig_aa"] != A["s_new_aa"]) \
            & (A["s_valid"] != 0)
        L["s_syn"] = (~changed.any(axis=1)).tolist()
        # bulk-convert the small per-codon planes ONCE: the (E, K)/
        # (E, 4) rows used to be extracted per event inside
        # _impact_text_l — 4-8 numpy row+tolist calls per indel/sub
        for plane in ("s_valid", "s_orig_aa", "s_new_aa", "s_aapos",
                      "aa4", "maa4", "aa4_valid", "maa4_valid"):
            if plane in A:
                L[plane] = A[plane].tolist()
    motif_text = ["[unknown]"] + [f"motif {m}" for m in motifs]
    # the host slices the 9bp context strings (byte-faithful for IUPAC
    # ambiguity characters the int8 code space collapses) — one
    # vectorized gather for the whole batch; <9bp references keep the
    # scalar degenerate-clamp path of get_ref_context
    ref_len = len(refseq)
    wb = None
    if ref_len >= 9:
        ru = np.frombuffer(refseq.upper(), np.uint8)
        rl = np.fromiter((ev.rloc for ev in events), np.int64,
                         len(events))
        ctxstart = np.clip(rl - 4, 0, ref_len - 9)
        wb = ru[ctxstart[:, None] + np.arange(9)].tobytes()
    out = []
    for k, ev in enumerate(events):
        if defer is not None and defer[k]:
            out.append(analyze_event_host(ev, refseq, skip_codan,
                                          motifs))
            continue
        ev.evtbases = ev.evtbases.upper()
        aa = chr(L["aa"][k])
        aapos = L["aapos"][k]
        if wb is not None:
            k9 = 9 * k
            rctx = wb[k9:k9 + 9]
        else:
            rctx = get_ref_context(refseq, ev.rloc)[0]
        if L["hpoly"][k]:
            status = "homopolymer"
        else:
            status = motif_text[L["motif"][k]]
        impact = ""
        if not skip_codan:
            impact = _impact_text_l(ev, k, L, strict_subs, refseq,
                                    skip_codan, motifs)
        out.append((aa, aapos, rctx, status, impact))
    return out


def analyze_events_columnar(refseq: bytes, events,
                            skip_codan: bool = False,
                            motifs=DEFAULT_MOTIFS,
                            max_ev: int = HOST_MAX_EV) -> list:
    """Columnar host analysis of a batch of DiffEvents against one
    reference: a list of (aa, aapos, rctx, status, impact) tuples in
    event order, byte-identical to mapping ``analyze_event_host`` over
    the batch (events the formulas can't reproduce exactly are routed
    there — see the module docstring)."""
    if not events:
        return []
    results: dict[int, tuple] = {}
    small = [ev for ev in events
             if len(ev.evtbases) <= max_ev and len(ev.evtsub) <= max_ev]
    if small:
        ref_len = len(refseq)
        ev = pack_events_np(small, max_ev, bucket=0)
        # scalar-route suspicious lanes: non-ACGT event bases always;
        # windows touching non-ACGT reference bases when the reference
        # holds any (code-space vs byte-space divergence, see module
        # docstring)
        suspicious = (
            ((ev["evtbases"] >= CODE_N) & (ev["evtbases"] != PAD))
            .any(axis=1)
            | ((ev["evtsub"] >= CODE_N) & (ev["evtsub"] != PAD))
            .any(axis=1))
        ref_codes = encode(refseq.upper())
        ref_h = np.full(ref_len + max_ev + 3, PAD, np.int8)
        ref_h[:ref_len] = ref_codes
        mot_codes, mot_lens = pack_motifs_np(motifs)
        host = host_ctx_scan(ref_h, ref_len, ev, mot_codes, mot_lens,
                             max_codons=max_ev // 3 + 2,
                             skip_codan=skip_codan)
        if (ref_codes >= CODE_N).any():
            suspicious |= (host["rctx"] >= CODE_N).any(axis=1)
        for e, r in zip(small, assemble_results(
                small, host, refseq, motifs, skip_codan,
                defer=suspicious.tolist(), strict_subs=True)):
            results[id(e)] = r
    for e in events:
        if id(e) not in results:   # oversized: scalar path
            results[id(e)] = analyze_event_host(e, refseq, skip_codan,
                                                motifs)
    return [results[id(e)] for e in events]


def emit_batch_rows(batch, analyzed: dict, f,
                    summary: Summary | None) -> None:
    """Write one batch's report rows from per-event analysis results —
    the emit path shared by the device finish path and the host
    columnar path.  One writer call per batch; the default assembly is
    the fused batch formatter (``report/rowbytes.py``) with the
    per-event truncation rules and summary counting inlined, and
    ``PWASM_HOST_FORMAT=0`` routes back to the scalar
    ``format_event_row`` loop (mirroring ``PWASM_HOST_COLUMNAR=0``) so
    a formatting regression is bisectable in production."""
    from pwasm_tpu.report.rowbytes import (format_batch_block,
                                           vector_format_enabled)

    if vector_format_enabled():
        f.write(format_batch_block(batch, analyzed, summary))
        return
    rows: list[str] = []
    for aln, rlabel, tlabel, _refseq in batch:
        rows.append(format_header(aln, rlabel, tlabel))
        if summary is not None:
            summary.add_alignment(aln)
            for di in aln.tdiffs:
                aa, aapos, rctx, status, impact = analyzed[id(di)]
                summary.add_event(di, status, impact)
                rows.append(format_event_row(di, aa, aapos, rctx,
                                             status, impact))
        else:
            for di in aln.tdiffs:
                aa, aapos, rctx, status, impact = analyzed[id(di)]
                rows.append(format_event_row(di, aa, aapos, rctx,
                                             status, impact))
    f.write("".join(rows))


def _analyze_batch(batch, skip_codan: bool, motifs) -> dict:
    """Columnar analysis of one report batch: events group per distinct
    refseq (like the device path), one vectorized analysis per group;
    returns ``{id(event): (aa, aapos, rctx, status, impact)}``."""
    groups: dict[bytes, list] = {}
    for aln, _rl, _tl, refseq in batch:
        groups.setdefault(refseq, []).extend(aln.tdiffs)
    analyzed: dict[int, tuple] = {}
    for refseq, events in groups.items():
        for ev, r in zip(events, analyze_events_columnar(
                refseq, events, skip_codan, motifs)):
            analyzed[id(ev)] = r
    return analyzed


def submit_diff_info_batch_host(batch, f, skip_codan: bool = False,
                                motifs=DEFAULT_MOTIFS, summary=None,
                                stats=None, executor=None):
    """Stage one host report batch through the analyze→format pipeline
    and return a ``finish() -> None`` closure that writes the assembled
    block.

    With ``executor`` (the CLI's single host-pipeline worker) the
    columnar analysis and the block assembly of batch k run on the
    worker thread while the main thread parses/extracts batch k+1 and
    merges the MSA — the host twin of the device path's two-deep
    in-flight flush pipeline.  The big numpy analysis ops and the
    native extraction release the GIL, so the overlap is real.  finish
    closures are called in submit order, so rows land in input order
    and the ``--resume`` clean-prefix contract holds.  ``executor=None``
    runs everything synchronously (the ``PWASM_HOST_PIPELINE=0``
    hatch).

    The run ``summary`` is folded on the worker (batches are FIFO
    through ONE worker, so the folds are ordered); the per-stage walls
    land in ``stats`` (``host_analyze_s``/``host_format_s``).

    A PwasmError during analysis (the reference's fatal
    modseq-vs-evtsub verification) surfaces in finish(): nothing of
    this batch has been written yet, so the scalar replay reproduces
    the progressive writes up to the failing event, then raises the
    scalar-exact error — the same observable behavior, bytes and
    message, as the per-line scalar loop."""
    import time as _time

    from pwasm_tpu.report.rowbytes import (format_batch_block,
                                           vector_format_enabled)

    def work() -> str:
        t0 = _time.perf_counter()
        analyzed = _analyze_batch(batch, skip_codan, motifs)
        t1 = _time.perf_counter()
        if vector_format_enabled():
            block = format_batch_block(batch, analyzed, summary)
        else:
            # scalar-format hatch: the per-row loop assembles into an
            # in-memory sink — the write itself stays in finish(), in
            # submit order
            import io
            sink = io.StringIO()
            emit_batch_rows(batch, analyzed, sink, summary)
            block = sink.getvalue()
        t2 = _time.perf_counter()
        if stats is not None:
            stats.host_analyze_s += t1 - t0
            stats.host_format_s += t2 - t1
        return block

    fut = executor.submit(work) if executor is not None else None

    def finish() -> None:
        try:
            block = fut.result() if fut is not None else work()
        except PwasmError:
            for aln, rlabel, tlabel, refseq in batch:
                print_diff_info(aln, rlabel, tlabel, f, refseq,
                                skip_codan=skip_codan, motifs=motifs,
                                summary=summary)
            raise   # unreachable in practice: the replay raises first
        f.write(block)

    return finish


def print_diff_info_batch_host(batch, f, skip_codan: bool = False,
                               motifs=DEFAULT_MOTIFS, summary=None,
                               stats=None) -> None:
    """Synchronous analyze+emit of one host report batch (the
    pipeline's submit+finish fused — kept as the direct-call surface
    for tests and library users)."""
    submit_diff_info_batch_host(batch, f, skip_codan, motifs, summary,
                                stats, executor=None)()
