"""The per-alignment diff report (.dfa) and its biology analysis.

Byte-parity port of the reference's L3 layer (pafreport.cpp:721-955):
``getRefContext``, ``hpolyCheck``, ``mmotifCheck``, ``predictImpact`` and
``PAFAlignment::printDiffInfo``.  Also implements the event summary counters
that the reference documents for ``-s`` but never writes (quirk SURVEY.md
§2.5.1) — here they are real.

The device path (`pwasm_tpu.ops.ctx_scan`) computes the same quantities as
batched tensors; this module is the bit-exact scalar ground truth and the
formatter of record.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import IO

from pwasm_tpu.core.config import DEFAULT_MOTIFS
from pwasm_tpu.core.dna import translate_codon
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.events import DiffEvent, PafAlignment

MAX_EVLEN = 12  # maximum event length to display (pafreport.cpp:919)


def get_ref_context(refseq: bytes, rloc: int) -> tuple[bytes, int]:
    """9-base reference window centered (-4/+4) on ``rloc`` with edge
    clamping; returns (window, event offset within window).
    Reference: getRefContext (pafreport.cpp:721-733).

    Parity note: at the right edge the reference applies the window shift to
    ``evtloc`` with the wrong sign (pafreport.cpp:726-728), so events near
    the sequence end report a too-small local offset (0 instead of 8 for the
    last base of a 25bp query).  That skews hpolyCheck's overlap test for
    right-edge events; preserved bit-for-bit."""
    ctxstart = rloc - 4
    evtloc = 4
    if ctxstart < 0:
        evtloc += ctxstart
        ctxstart = 0
    elif ctxstart + 8 >= len(refseq):
        evtloc += len(refseq) - ctxstart - 9
        ctxstart = len(refseq) - 9
        if ctxstart < 0:  # degenerate <9bp reference; reference reads OOB
            evtloc += ctxstart
            ctxstart = 0
    return refseq[ctxstart:ctxstart + 9].upper(), evtloc


def hpoly_check(evtbases: bytes, rctx: bytes, rctxloc: int) -> bool:
    """Homopolymer attribution: all event bases identical AND a 4-run of
    that base occurs in the 9bp window overlapping the event position.
    Reference: hpolyCheck (pafreport.cpp:735-748)."""
    if not evtbases:
        return False
    if len(evtbases) > 1 and any(b != evtbases[0] for b in evtbases[1:]):
        return False
    cseed = evtbases[0:1] * 4
    l = rctx.find(cseed)
    return 0 <= l <= rctxloc <= l + 4


def mmotif_check(rctx: bytes, motifs=DEFAULT_MOTIFS) -> tuple[int, str]:
    """First motif found anywhere in the 9bp window wins; returns (1-based
    motif index or 0, status text).  Reference: mmotifCheck
    (pafreport.cpp:751-763)."""
    for m, motif in enumerate(motifs):
        if rctx.find(motif.encode()) >= 0:
            return m + 1, f"motif {motif}"
    return 0, ""


def predict_impact(di: DiffEvent, refseq: bytes, r_trloc: int) -> str:
    """Codon-impact prediction.  Reference: predictImpact
    (pafreport.cpp:801-883).

    ``r_trloc`` is the translation-window start (one codon before the event
    codon, clamped to 0).  Note the reference's GStr(ptr, len) capacity
    quirk (SURVEY.md §2.5.9) makes both the original and modified sequences
    the *entire* reference suffix from ``r_trloc`` — preserved here.
    """
    r_trseq = refseq[r_trloc:]
    modseq = bytearray(r_trseq)
    if di.evt == "S":
        aaofs = -1
        aamods: list[int] = []
        for i in range(len(di.evtbases)):
            p = di.rloc - r_trloc + i
            if modseq[p:p + 1].upper() != di.evtsub[i:i + 1].upper():
                raise PwasmError(
                    f"Error: modseq[{p}] not matching di.evtsub[{i}] !\n")
            modseq[p] = di.evtbases[i]
            ao = p // 3
            if ao != aaofs:
                aaofs = ao
                aamods.append(ao)
        parts: list[str] = []
        mod_b = bytes(modseq)   # one copy for all modified codons
        for ao in aamods:
            aa = translate_codon(r_trseq, ao * 3)
            maa = translate_codon(mod_b, ao * 3)
            if aa != maa:  # not a synonymous codon
                aapos = ao + di.rloc // 3
                s = f"AA{aapos}|{aa}:{maa}"
                if maa == ".":
                    s += f"|premature stop at AA{aapos}"
                parts.append(s)
        return ", ".join(parts) if parts else "synonymous"
    if di.evt == "I":
        pos = di.rloc - r_trloc
        modseq[pos:pos] = di.evtbases
    elif di.evt == "D":
        pos = di.rloc - r_trloc
        del modseq[pos:pos + di.evtlen]
    else:
        raise PwasmError(f"Error: unrecognized editing event ({di.evt})!\n")
    # for I/D, look for a premature stop codon down the road
    aamodc = 0
    aa4: list[str] = []
    maa4: list[str] = []
    txt = ""
    i = 0
    mod_b = bytes(modseq)   # ONE copy — the scan below is per codon,
    #                         and modseq is the whole reference suffix
    while i + 2 < len(mod_b):
        aamod = translate_codon(mod_b, i)
        if aamod == ".":
            txt = f"premature stop at AA{1 + (i + r_trloc) // 3}"
            break
        if i > 0 and aamodc < 4:
            aamodc += 1
            if i + 2 < len(r_trseq):
                aa4.append(translate_codon(r_trseq, i))
            maa4.append(aamod)
        i += 3
    if not txt and aa4 and maa4:
        txt = f"frame shift {''.join(aa4)}+:{''.join(maa4)}+"
    return txt


@dataclass
class Summary:
    """Event summary counters — the reference's documented-but-unwritten
    ``-s`` output (pafreport.cpp:20,274; SURVEY.md §5), implemented as a
    trivial reduction over the event stream."""

    alignments: int = 0
    events: dict = field(default_factory=lambda: {"S": 0, "I": 0, "D": 0})
    bases: dict = field(default_factory=lambda: {"S": 0, "I": 0, "D": 0})
    status: dict = field(default_factory=lambda: {
        "homopolymer": 0, "motif": 0, "unknown": 0})
    impact: dict = field(default_factory=lambda: {
        "synonymous": 0, "nonsynonymous": 0, "premature_stop": 0,
        "frame_shift": 0})
    aligned_bases: int = 0

    def add_alignment(self, aln: PafAlignment) -> None:
        self.alignments += 1
        al = aln.alninfo
        self.aligned_bases += al.r_alnend - al.r_alnstart

    def add_event(self, di: DiffEvent, status: str, impact: str) -> None:
        evt = di.evt
        events = self.events
        events[evt] = events.get(evt, 0) + 1
        nb = len(di.evtbases) if evt != "D" else di.evtlen
        bases = self.bases
        bases[evt] = bases.get(evt, 0) + nb
        if status == "homopolymer":
            self.status["homopolymer"] += 1
        elif status.startswith("motif"):
            self.status["motif"] += 1
        else:
            self.status["unknown"] += 1
        if impact:
            if impact == "synonymous":
                self.impact["synonymous"] += 1
            elif "premature stop" in impact:
                self.impact["premature_stop"] += 1
            elif impact.startswith("frame shift"):
                self.impact["frame_shift"] += 1
            else:
                self.impact["nonsynonymous"] += 1

    def fold_event_counts(self, events: dict, bases: dict,
                          status: dict, impact: dict) -> None:
        """Fold one batch's pre-classified event counters in bulk — the
        vectorized emit path (``report/rowbytes.py``) classifies events
        in its assembly loop and lands the whole batch here in a dozen
        dict adds, instead of paying :meth:`add_event` per event."""
        for k, v in events.items():
            self.events[k] = self.events.get(k, 0) + v
        for k, v in bases.items():
            self.bases[k] = self.bases.get(k, 0) + v
        for k, v in status.items():
            self.status[k] += v
        for k, v in impact.items():
            self.impact[k] += v

    def write(self, f: IO[str]) -> None:
        # one assembled block, one write (the same batching contract as
        # the report emit path — the per-line appends were measurable
        # under the warm-serve daemon's per-job summaries)
        lines = ["# pwasm-tpu event summary\n",
                 f"alignments\t{self.alignments}\n",
                 f"aligned_query_bases\t{self.aligned_bases}\n",
                 f"events_total\t{sum(self.events.values())}\n"]
        for k, label in (("S", "substitutions"), ("I", "insertions"),
                         ("D", "deletions")):
            lines.append(f"{label}\t{self.events.get(k, 0)}"
                         f"\t{self.bases.get(k, 0)} bases\n")
        for k in ("homopolymer", "motif", "unknown"):
            lines.append(f"cause_{k}\t{self.status[k]}\n")
        for k in ("synonymous", "nonsynonymous", "premature_stop",
                  "frame_shift"):
            lines.append(f"impact_{k}\t{self.impact[k]}\n")
        f.write("".join(lines))


def _truncate_display(data: bytes) -> bytes:
    """``[len]`` truncation for long event strings (pafreport.cpp:928-941)."""
    if len(data) > MAX_EVLEN:
        return b"[" + str(len(data)).encode() + b"]"
    return data


def analyze_event_host(di: DiffEvent, refseq: bytes, skip_codan: bool,
                       motifs=DEFAULT_MOTIFS):
    """Scalar analysis of one event: (aa, aapos, rctx, status, impact).
    NB: upper-cases ``di.evtbases`` in place, like the reference's
    printDiffInfo loop head (pafreport.cpp:895)."""
    di.evtbases = di.evtbases.upper()
    aapos = di.rloc // 3
    aa = translate_codon(refseq, 3 * aapos)
    aapos += 1
    rctx, rctxloc = get_ref_context(refseq, di.rloc)
    status = "homopolymer" if hpoly_check(di.evtbases, rctx, rctxloc) else ""
    r_trloc = 3 * (aapos - 2)  # start editing one codon before
    if r_trloc < 0:
        r_trloc = 0
    if not status:
        _, status = mmotif_check(rctx, motifs)
    impact = ""
    if not skip_codan:
        impact = predict_impact(di, refseq, r_trloc)
    if not status:
        status = "[unknown]"
    return aa, aapos, rctx, status, impact


def format_event_row(di: DiffEvent, aa: str, aapos: int, rctx: bytes,
                     status: str, impact: str) -> str:
    """One TSV report row (pafreport.cpp:942-953), shared by the host and
    device analysis paths."""
    tcontext = di.tctx
    if len(tcontext) > 10 + MAX_EVLEN:
        dlen = len(tcontext) - 10
        tcontext = (di.tctx[:5] + b"[" + str(dlen).encode() + b"]"
                    + di.tctx[-5:])
    evtbases = di.evtbases if len(di.evtbases) <= MAX_EVLEN \
        else _truncate_display(di.evtbases)
    tctx_s = tcontext.decode("ascii", "replace")
    rctx_s = rctx.decode("ascii", "replace")
    eb = evtbases.decode("ascii", "replace")
    if di.evt == "S":
        es = _truncate_display(di.evtsub).decode("ascii", "replace")
        mid = f"{es}:{eb}"
    elif di.evt == "I":
        mid = f":{eb}"
    else:
        mid = f"{eb}:"
    return (f"{di.evt}\t{di.rloc + 1}\t{aapos}({aa})\t{mid}\t"
            f"{di.tloc + 1}\t{tctx_s}\t{rctx_s}\t{status}\t{impact}\n")


def format_header(aln: PafAlignment, rlabel: str, tlabel: str) -> str:
    """The per-alignment report header line (pafreport.cpp:886-892)."""
    al = aln.alninfo
    # degenerate zero-length query: the reference's C++ double division
    # yields NaN and keeps going; mirror that instead of raising
    cov = ((al.r_alnend - al.r_alnstart) * 100.00 / al.r_len
           if al.r_len else float("nan"))
    if not rlabel:
        return (f">{tlabel} coverage:{cov:.2f} score={aln.alnscore} "
                f"edit_distance={aln.edist}\n")
    return (f">{rlabel}--{tlabel} coverage:{cov:.2f} "
            f"score={aln.alnscore} edit_distance={aln.edist}\n")


def print_diff_info(aln: PafAlignment, rlabel: str, tlabel: str, f: IO[str],
                    refseq: bytes, skip_codan: bool = False,
                    motifs=DEFAULT_MOTIFS,
                    summary: Summary | None = None) -> None:
    """Emit the per-alignment diff report rows.
    Reference: PAFAlignment::printDiffInfo (pafreport.cpp:885-955).

    ``refseq`` is the *forward* query sequence (upper-case).
    """
    f.write(format_header(aln, rlabel, tlabel))
    if summary is not None:
        summary.add_alignment(aln)
    for di in aln.tdiffs:
        aa, aapos, rctx, status, impact = analyze_event_host(
            di, refseq, skip_codan, motifs)
        if summary is not None:
            summary.add_event(di, status, impact)
        f.write(format_event_row(di, aa, aapos, rctx, status, impact))
