"""Report writers: the .dfa diff report, summary counters, MSA writers."""

from pwasm_tpu.report.diff_report import (  # noqa: F401
    get_ref_context,
    hpoly_check,
    mmotif_check,
    predict_impact,
    print_diff_info,
    Summary,
)
