"""pwasm-tpu: a TPU-native framework for PAF alignment diff analysis and MSA
consensus calling.

Capabilities mirror the reference toolchain (``pafreport`` + the GapAssem MSA
engine, see SURVEY.md): ingest minimap2 PAF+``cs`` alignments of query
sequences against many targets, reconstruct each target from the ``cs`` diff
string, report every indel/substitution with sequence context (homopolymers,
methylation motifs) and codon-impact prediction, and build progressive MSAs
with consensus calling.

Architecture (TPU-first, not a translation):

- ``pwasm_tpu.core``   — host data model: DNA tables, FASTA faidx reader,
  PAF/cs/CIGAR parsing, diff-event extraction (ground truth for everything).
- ``pwasm_tpu.align``  — gapped-sequence/MSA engine: tensorised gap
  bookkeeping, progressive merge, consensus, clip refinement (bit-exact CPU
  path).
- ``pwasm_tpu.ops``    — JAX/Pallas device kernels: per-column consensus
  vote, batched banded affine-gap DP (anti-diagonal wavefront), vectorized
  variant-context/codon scan.
- ``pwasm_tpu.parallel`` — ``jax.sharding`` mesh pipeline: batch-axis data
  parallelism, depth-axis ``psum`` of pileup counts, column-axis sequence
  parallelism.
- ``pwasm_tpu.report`` — byte-compatible ``.dfa`` diff report, ``.mfa`` MSA,
  ACE and contig-info writers, plus the event summary counters.
- ``pwasm_tpu.native`` — C++ host core (fast PAF/cs/CIGAR tokenizers, FASTA
  index, 2-bit packing) with ctypes bindings and a pure-Python fallback.
- ``pwasm_tpu.cli``    — ``pafreport``-compatible command line front end with
  ``--device={cpu,tpu}``.
"""

__version__ = "0.1.0"
