"""Declarative SLO/alert rule engine over the live MetricsRegistry.

The self-monitoring half of the observability story (ISSUE 14):
everything PRs 6/11/13 built is *passive* — metrics a human must
read.  This module closes the loop: a small set of declarative rules
is evaluated on a timer over the same :class:`~pwasm_tpu.obs.metrics.
MetricsRegistry` the exposition serves, and firing/resolved
transitions become event-log records, metric families
(``pwasm_alerts_firing{rule}`` /
``pwasm_alert_transitions_total{rule,state}``), and the machine-
readable **health verdict** the ``health`` protocol verb returns —
the substrate auto-scaling hooks and orchestrator probes (k8s
liveness, pagers) consume.

Three rule kinds, all plain dicts (JSON-loadable — ``serve/route
--slo-rules=FILE`` adds user rules to the defaults in
``obs/catalog.py``):

``threshold``
    compare a gauge/counter's current value (any labeled cell
    matches) against ``value`` via ``op``; optional ``divide_by``
    names a second metric whose summed value becomes the denominator
    (``queue_depth / max_queue > 0.8``); optional ``for_s`` requires
    the condition to hold continuously before firing (a one-scrape
    blip must not page).
``rate``
    the increase of a counter over the trailing ``window_s`` compared
    via ``op``/``value`` — "any journal replay in the last 5 minutes".
    ``baseline: "zero"`` counts the value at the engine's first sample
    as an increase from zero (a replay that happened BEFORE the engine
    started — i.e. at daemon startup — still alerts for one window).
``burn_rate``
    the classic multi-window error-budget burn over a latency
    histogram: the fraction of observations above ``objective_s``
    within the trailing ``short_s`` AND ``long_s`` windows must BOTH
    exceed ``budget * burn`` to fire (the long window keeps a steady
    slow-burn visible, the short window makes the alert resolve fast
    once the bleeding stops).

Severity is ``warn`` or ``page``; the engine's verdict is ``failing``
if any page-severity rule fires, ``degraded`` if only warnings fire,
``ok`` otherwise — rendered as exit codes 0/1/2 by ``pwasm-tpu health
--exit-code`` for orchestrator probes.

jax-free and stdlib-only like the rest of ``pwasm_tpu/obs/`` (gated
by ``qa/check_supervision.py::find_slo_violations``), and
evaluation never raises into the serving loop it monitors: a rule
over a metric that does not exist (a user rule with a typo) simply
reports no data.
"""

from __future__ import annotations

import json
import threading
import time

SEVERITIES = ("warn", "page")
KINDS = ("threshold", "rate", "burn_rate")
OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

# verdict ranking: worst-of aggregation (the router folds member
# verdicts with max over these ranks)
VERDICT_RANK = {"ok": 0, "degraded": 1, "failing": 2}
RANK_VERDICT = {v: k for k, v in VERDICT_RANK.items()}


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_rule(rule: dict) -> dict:
    """Validate one rule dict (raises ``ValueError`` with a pointed
    diagnostic) and return it normalized — shared by the default-rule
    catalog (validated at import by the tests) and ``--slo-rules``
    user files, so the two grammars cannot drift."""
    if not isinstance(rule, dict):
        raise ValueError(f"rule must be an object, got {type(rule).__name__}")
    name = rule.get("name")
    if not isinstance(name, str) \
            or not name.replace("_", "a").isalnum() \
            or name != name.lower():
        raise ValueError(f"rule name {name!r} must be lower_snake_case")
    out = {"name": name}
    sev = rule.get("severity", "warn")
    if sev not in SEVERITIES:
        raise ValueError(f"rule {name}: severity {sev!r} not in "
                         f"{SEVERITIES}")
    out["severity"] = sev
    kind = rule.get("kind", "threshold")
    if kind not in KINDS:
        raise ValueError(f"rule {name}: kind {kind!r} not in {KINDS}")
    out["kind"] = kind
    metric = rule.get("metric")
    if not isinstance(metric, str) or not metric:
        raise ValueError(f"rule {name}: metric must be a metric name")
    out["metric"] = metric
    out["runbook"] = str(rule.get("runbook") or "")
    if kind in ("threshold", "rate"):
        op = rule.get("op", ">")
        if op not in OPS:
            raise ValueError(f"rule {name}: op {op!r} not in "
                             f"{sorted(OPS)}")
        out["op"] = op
        if not _num(rule.get("value")):
            raise ValueError(f"rule {name}: value must be a number")
        out["value"] = float(rule["value"])
    if kind == "threshold":
        div = rule.get("divide_by")
        if div is not None and (not isinstance(div, str) or not div):
            raise ValueError(f"rule {name}: divide_by must be a "
                             "metric name")
        out["divide_by"] = div
        for_s = rule.get("for_s", 0.0)
        if not _num(for_s) or for_s < 0:
            raise ValueError(f"rule {name}: for_s must be >= 0")
        out["for_s"] = float(for_s)
    elif kind == "rate":
        window = rule.get("window_s", 300.0)
        if not _num(window) or window <= 0:
            raise ValueError(f"rule {name}: window_s must be > 0")
        out["window_s"] = float(window)
        baseline = rule.get("baseline", "first")
        if baseline not in ("first", "zero"):
            raise ValueError(f"rule {name}: baseline must be "
                             "'first' or 'zero'")
        out["baseline"] = baseline
    else:   # burn_rate
        for key, dflt, lo in (("objective_s", None, 0.0),
                              ("budget", None, 0.0),
                              ("short_s", 60.0, 0.0),
                              ("long_s", 300.0, 0.0),
                              ("burn", 1.0, 0.0)):
            v = rule.get(key, dflt)
            if not _num(v) or not v > lo:
                raise ValueError(f"rule {name}: {key} must be a "
                                 f"number > {lo}")
            out[key] = float(v)
        if out["short_s"] >= out["long_s"]:
            raise ValueError(f"rule {name}: short_s must be < long_s")
    unknown = set(rule) - set(out)
    if unknown:
        raise ValueError(f"rule {name}: unknown field(s) "
                         f"{sorted(unknown)}")
    return out


def parse_rules(rules) -> list[dict]:
    """Validate a list of rule dicts; duplicate names are an error
    (one name = one alert series)."""
    if not isinstance(rules, list):
        raise ValueError("SLO rules must be a JSON list of rule "
                         "objects")
    out = []
    seen: set[str] = set()
    for r in rules:
        v = validate_rule(r)
        if v["name"] in seen:
            raise ValueError(f"duplicate rule name {v['name']!r}")
        seen.add(v["name"])
        out.append(v)
    return out


def load_rules_file(path: str) -> list[dict]:
    """Parse a ``--slo-rules=FILE`` JSON document (a list of rule
    dicts).  Raises ``ValueError`` with a diagnostic naming the file
    on any problem — the serve/route entry points render it as the
    usual usage error."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read --slo-rules {path}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"--slo-rules {path} is not valid JSON: {e}")
    try:
        return parse_rules(doc)
    except ValueError as e:
        raise ValueError(f"--slo-rules {path}: {e}")


def merge_rules(defaults: list[dict],
                extra: list[dict] | None) -> list[dict]:
    """Defaults + user rules; a user rule with a default's name
    REPLACES it (so an operator can retune a shipped threshold
    without forking the whole set)."""
    if not extra:
        return list(defaults)
    by_name = {r["name"]: r for r in defaults}
    for r in extra:
        by_name[r["name"]] = r
    return list(by_name.values())


class _RuleState:
    """Per-rule evaluation state: firing latch, pending clock
    (``for_s``), the bounded sample history rate/burn rules
    difference against, and the never-evicted FIRST sample (the
    ``baseline: "first"`` anchor — it must survive no matter how
    densely an external prober forces evaluations)."""

    __slots__ = ("rule", "firing", "since", "pending_since",
                 "detail", "value", "samples", "first")

    def __init__(self, rule: dict):
        self.rule = rule
        self.firing = False
        self.since: float | None = None        # wall, fire time
        self.pending_since: float | None = None
        self.detail = ""
        self.value: float | None = None
        self.first: tuple | None = None
        from collections import deque
        # baselines only (the current value is read live): appends
        # are TIME-SPACED at window/128, so the deque covers the full
        # window at any evaluation cadence — a health prober hammering
        # evaluate() can never evict the left-of-window baseline
        # (maxlen is a pure memory backstop)
        self.samples: deque = deque(maxlen=4096)

    def sample(self, now: float, row: tuple, window_s: float) -> None:
        """Record ``row`` (t-first) as baseline history, time-spaced."""
        if self.first is None:
            self.first = row
        if not self.samples \
                or now - self.samples[-1][0] >= window_s / 128.0:
            self.samples.append(row)


class SloEngine:
    """Evaluate a rule set over a registry on a timer.

    ``metrics`` is the ``build_slo_metrics`` dict (``firing`` gauge +
    ``transitions`` counter — obs/catalog.py); ``on_event`` receives
    ``(event, **fields)`` for firing/resolved transitions (the daemon
    wires it to ``Observability.event`` so transitions land in the
    NDJSON log in order).  Both optional — the engine also runs bare
    in tests.

    Thread-safety: ``evaluate`` takes the engine lock for the whole
    pass (the accept loop, the health verb, and the stats verb may
    all trigger it); registry reads snapshot under each family's own
    lock.
    """

    def __init__(self, registry, rules: list[dict],
                 metrics: dict | None = None, on_event=None,
                 eval_interval_s: float = 1.0):
        self.registry = registry
        self.rules = parse_rules(list(rules))
        self.metrics = metrics or {}
        self.on_event = on_event
        self.eval_interval_s = max(0.01, float(eval_interval_s))
        self._states = {r["name"]: _RuleState(r) for r in self.rules}
        self._lock = threading.Lock()
        self._last_eval = 0.0       # monotonic
        self._evaluations = 0
        # a rule's firing gauge must EXIST from the start (an absent
        # series looks like a scrape gap, not health)
        firing = self.metrics.get("firing")
        if firing is not None:
            for r in self.rules:
                firing.set(0, rule=r["name"])

    # ---- evaluation ----------------------------------------------------
    def due(self) -> bool:
        return time.monotonic() - self._last_eval \
            >= self.eval_interval_s

    def evaluate(self, now: float | None = None) -> dict:
        """One evaluation pass; returns :meth:`verdict`.  Never raises
        — a broken rule (user typo, schema drift) evaluates as
        no-data, not a crashed serving loop."""
        now = time.time() if now is None else now
        with self._lock:
            self._last_eval = time.monotonic()
            self._evaluations += 1
            for st in self._states.values():
                try:
                    cond, value, detail = self._eval_rule(st, now)
                except Exception as e:      # defensive by contract
                    cond, value = False, None
                    detail = f"rule evaluation error: {e}"
                self._transition(st, cond, value, detail, now)
            return self._verdict_locked(now)

    def _metric_cells(self, name: str):
        m = self.registry.get(name)
        return m.snapshot_cells() if m is not None else []

    def _scalar_cells(self, name: str) -> list[tuple[dict, float]]:
        out = []
        for labels, snap in self._metric_cells(name):
            if _num(snap):
                out.append((labels, float(snap)))
        return out

    def _eval_rule(self, st: _RuleState, now: float):
        r = st.rule
        if r["kind"] == "threshold":
            return self._eval_threshold(r)
        if r["kind"] == "rate":
            return self._eval_rate(st, now)
        return self._eval_burn(st, now)

    def _eval_threshold(self, r: dict):
        cells = self._scalar_cells(r["metric"])
        if not cells:
            return False, None, "no data"
        denom = None
        if r.get("divide_by"):
            denom = sum(v for _l, v in
                        self._scalar_cells(r["divide_by"]))
            if denom <= 0:
                return False, None, "no data (zero denominator)"
        op = OPS[r["op"]]
        worst = None
        for labels, v in cells:
            val = v / denom if denom is not None else v
            if op(val, r["value"]):
                # any-cell semantics: the FIRST matching cell names
                # the offender (labels in the detail)
                lbl = ",".join(f"{k}={v2}" for k, v2 in
                               sorted(labels.items()))
                detail = (f"{r['metric']}"
                          + (f"{{{lbl}}}" if lbl else "")
                          + (f" / {r['divide_by']}"
                             if denom is not None else "")
                          + f" = {round(val, 6)} {r['op']} "
                          f"{r['value']}")
                return True, round(val, 6), detail
            if worst is None:
                worst = val
        return False, round(worst, 6) if worst is not None else None, ""

    def _counter_total(self, name: str) -> float | None:
        cells = self._scalar_cells(name)
        if not cells:
            return None
        return sum(v for _l, v in cells)

    def _eval_rate(self, st: _RuleState, now: float):
        r = st.rule
        total = self._counter_total(r["metric"])
        if total is None:
            # a REGISTERED family with no cells truly reads zero (a
            # counter nobody incremented yet) — only an unknown
            # metric name (user-rule typo) is genuinely no-data.
            # Sampling the zero matters: the first increment must
            # diff against it, not become the invisible baseline.
            if self.registry.get(r["metric"]) is None:
                return False, None, "no data"
            total = 0.0
        window = r["window_s"]
        st.sample(now, (now, total), window)
        # baseline: the newest sample at or before the window's left
        # edge, else the never-evicted first sample (or literal zero
        # when the rule says pre-engine history counts)
        base = None
        for t, v in st.samples:
            if t <= now - window:
                base = v
            else:
                break
        if base is None:
            base = 0.0 if r["baseline"] == "zero" else st.first[1]
        # drop samples that can no longer be a baseline (keep one
        # left-of-window sample)
        while len(st.samples) >= 2 \
                and st.samples[1][0] <= now - window:
            st.samples.popleft()
        increase = max(0.0, total - base)
        cond = OPS[r["op"]](increase, r["value"])
        detail = (f"increase({r['metric']}[{int(window)}s]) = "
                  f"{round(increase, 6)} {r['op']} {r['value']}") \
            if cond else ""
        return cond, round(increase, 6), detail

    def _eval_burn(self, st: _RuleState, now: float):
        r = st.rule
        m = self.registry.get(r["metric"])
        if m is None or not hasattr(m, "buckets"):
            return False, None, "no data"
        # sum the raw bucket counts over every labeled cell, then
        # count observations <= the smallest bucket bound covering the
        # objective (conservative: an objective between bounds uses
        # the bound ABOVE it)
        cells = m.snapshot_cells()
        if not cells:
            return False, None, "no data"
        n_b = len(m.buckets)
        counts = [0] * (n_b + 1)
        for _labels, snap in cells:
            raw = snap[0]
            for i, c in enumerate(raw):
                counts[i] += c
        # objective past every finite bound: the +Inf bucket cannot
        # distinguish meets-objective from misses, so ALL observations
        # count good — the rule degrades to never-fires (honest),
        # instead of flagging observations that may meet the objective
        le_idx = n_b + 1
        for i, b in enumerate(m.buckets):
            if b >= r["objective_s"]:
                le_idx = i + 1
                break
        total = sum(counts)
        good = sum(counts[:le_idx])
        st.sample(now, (now, total, good), r["long_s"])
        burns = []
        for window in (r["short_s"], r["long_s"]):
            base_tot = base_good = None
            for t, tot, g in st.samples:
                if t <= now - window:
                    base_tot, base_good = tot, g
                else:
                    break
            if base_tot is None:
                base_tot, base_good = st.first[1], st.first[2]
            d_tot = total - base_tot
            d_bad = max(0, d_tot - (good - base_good))
            frac = d_bad / d_tot if d_tot > 0 else 0.0
            burns.append((frac, d_tot))
        while len(st.samples) >= 2 \
                and st.samples[1][0] <= now - r["long_s"]:
            st.samples.popleft()
        limit = r["budget"] * r["burn"]
        cond = all(frac > limit and d_tot > 0
                   for frac, d_tot in burns)
        short_frac = round(burns[0][0], 6)
        detail = (f"{r['metric']} > {r['objective_s']}s fraction "
                  f"{short_frac} (short) / {round(burns[1][0], 6)} "
                  f"(long) > budget {limit}") if cond else ""
        return cond, short_frac, detail

    def _transition(self, st: _RuleState, cond: bool,
                    value, detail: str, now: float) -> None:
        r = st.rule
        if cond:
            if st.pending_since is None:
                st.pending_since = now
            held = now - st.pending_since
            if not st.firing and (r["kind"] != "threshold"
                                  or held >= r.get("for_s", 0.0)):
                st.firing = True
                st.since = now
                st.value, st.detail = value, detail
                self._note(r, "firing", value=value, detail=detail)
            elif st.firing:
                st.value, st.detail = value, detail
        else:
            st.pending_since = None
            if st.firing:
                st.firing = False
                st.since = None
                st.value, st.detail = value, ""
                self._note(r, "resolved", value=value)

    def _note(self, rule: dict, state: str, value=None,
              detail: str | None = None) -> None:
        firing = self.metrics.get("firing")
        if firing is not None:
            firing.set(1 if state == "firing" else 0,
                       rule=rule["name"])
        trans = self.metrics.get("transitions")
        if trans is not None:
            trans.inc(rule=rule["name"], state=state)
        if self.on_event is not None:
            try:
                self.on_event(
                    "alert_" + state, rule=rule["name"],
                    severity=rule["severity"], value=value,
                    detail=detail or None)
            except Exception:
                pass     # the never-raises contract

    # ---- verdict -------------------------------------------------------
    def firing(self) -> list[dict]:
        with self._lock:
            return self._firing_locked(time.time())

    def _firing_locked(self, now: float) -> list[dict]:
        out = []
        for st in self._states.values():
            if st.firing:
                out.append({
                    "rule": st.rule["name"],
                    "severity": st.rule["severity"],
                    "since_s": round(max(0.0, now - (st.since or now)),
                                     3),
                    "value": st.value,
                    "detail": st.detail,
                    "runbook": st.rule["runbook"] or None,
                })
        out.sort(key=lambda f: (f["severity"] != "page", f["rule"]))
        return out

    def verdict(self) -> dict:
        with self._lock:
            return self._verdict_locked(time.time())

    def _verdict_locked(self, now: float) -> dict:
        firing = self._firing_locked(now)
        if any(f["severity"] == "page" for f in firing):
            verdict = "failing"
        elif firing:
            verdict = "degraded"
        else:
            verdict = "ok"
        return {"verdict": verdict, "firing": firing,
                "rules": len(self.rules),
                "evaluations": self._evaluations}


def worst_verdict(*verdicts: str) -> str:
    """The fleet aggregation: worst of N verdict strings (unknown
    strings rank as degraded — an unparseable member answer must not
    read as healthy)."""
    rank = max((VERDICT_RANK.get(v, 1) for v in verdicts), default=0)
    return RANK_VERDICT[rank]


def verdict_exit_code(verdict: str) -> int:
    """``health --exit-code`` mapping: ok=0, degraded=1, failing=2
    (anything unrecognized ranks degraded, same rule as aggregation)."""
    return VERDICT_RANK.get(verdict, 1)
